"""Warm standby: sub-second host join (ISSUE 18).

A cold host joining a pod pays three serial costs before it answers
its first decision: mesh/device formation, XLA compilation of the
decision kernels, and limits configuration. The warm standby pays all
three BEFORE it is a member, so the join itself (server/resize.py
``join_host``) flips membership as a pure control-plane fact:

* **mesh** — the standby forms its HOST-LOCAL mesh at boot
  (``parallel.make_host_mesh``): since ISSUE 18 membership is not a
  `jax.distributed` formation property, so a single process can form,
  compile and serve without knowing which pod it will land in.
* **kernels** — :meth:`WarmStandby.warm` drives the jitted decision
  kernels through every power-of-two hit bucket the batcher can emit
  (``tpu/storage._bucket`` pads hit counts to pow2 precisely so there
  are few programs to compile), against a scratch table of the SAME
  capacity the serving storage uses — jit caches key on shapes, so a
  mismatched capacity would compile programs the serving path never
  reuses. With ``--xla-cache-dir`` the programs also persist to disk,
  so even the standby's own warm-up is fast after its first boot.
* **state** — the coordinator ships limits + the plan-cache seed over
  the ``join_admin``/``plan_seed`` lane kinds (armed here) before any
  routing changes, and the PR 15 migrate lane moves the joiner's shard
  slice AFTER the epoch bump, overlapped with serving.

``--standby off`` (the default) never constructs a WarmStandby and
never arms the join callbacks: wire format and construction stay
byte-identical to PR 17 (test-pinned).
"""

from __future__ import annotations

import logging
import time
from typing import Optional, Sequence

__all__ = ["WarmStandby", "METRIC_FAMILIES", "DEFAULT_WARM_BUCKETS"]

log = logging.getLogger("limitador_tpu.pod.standby")

#: metric families this module owns (cross-checked against
#: observability/metrics.py by the analysis registry pass)
METRIC_FAMILIES = (
    "standby_ready",
    "standby_warm_kernels",
    "standby_warm_seconds",
)

#: the pow2 hit buckets warmed by default: ``_bucket`` floors at 8 and
#: the batcher's adaptive chunking tops out well under 512 hits per
#: kernel launch in every shipped configuration
DEFAULT_WARM_BUCKETS = (8, 16, 32, 64, 128, 256, 512)


class WarmStandby:
    """Holds a formed, compiled, configured-but-memberless host ready
    for :meth:`PodResizeCoordinator.join_host` promotion.

    Wiring (``--standby on`` in server/__main__.py, or a test/bench
    harness): construct over the assembled frontend + coordinator,
    call :meth:`warm` once off the serving path, and the standby waits
    for a coordinator's ``join_admin`` adopt. Arming is explicit and
    separate from ``attach_resize`` so the default pod construction
    stays byte-identical to PR 17."""

    def __init__(
        self,
        frontend,
        coordinator,
        warm_buckets: Sequence[int] = DEFAULT_WARM_BUCKETS,
        table_capacity: Optional[int] = None,
    ):
        self.frontend = frontend
        self.coordinator = coordinator
        self.warm_buckets = tuple(
            sorted({int(b) for b in warm_buckets})
        )
        # jit programs key on the table shape: warm against the SAME
        # capacity the serving storage holds or the compiles are wasted
        if table_capacity is None:
            storage = getattr(frontend, "pipeline", None)
            storage = getattr(storage, "storage", None) or getattr(
                frontend._limiter, "storage", None
            )
            storage = getattr(storage, "counters", storage)
            table_capacity = getattr(storage, "capacity", None)
        self.table_capacity = int(table_capacity or 1024)
        self.ready = False
        self.warm_kernels = 0
        self.warm_seconds = 0.0
        # the join control plane: the coordinator answers adopt/limits
        # ops, the frontend imports shipped plan seeds, and the
        # frontend's library_stats carries the standby_* families
        frontend.lane.join_cb = coordinator.handle_join
        frontend.lane.plan_seed_cb = frontend.plan_seed_import
        frontend.standby = self

    def warm(self) -> dict:
        """Pre-compile the decision kernels at every configured pow2
        hit bucket (blocking; run at boot, never on a serving loop).
        Warm-up failure degrades to cold-compile-on-first-miss — it
        must never prevent the standby from becoming joinable."""
        started = time.time()
        compiled = 0
        try:
            compiled = self._compile_buckets()
        except Exception as exc:
            log.warning(f"standby kernel warm-up failed: {exc}")
        self.warm_seconds = round(time.time() - started, 6)
        self.warm_kernels = compiled
        self.ready = True
        self.frontend.events.emit(
            "standby_ready",
            kernels=compiled,
            buckets=len(self.warm_buckets),
            seconds=self.warm_seconds,
            capacity=self.table_capacity,
        )
        log.info(
            f"warm standby ready: {compiled} kernels over buckets "
            f"{list(self.warm_buckets)} in {self.warm_seconds:.3f}s "
            f"(table capacity {self.table_capacity})"
        )
        return {
            "ready": True,
            "kernels": compiled,
            "seconds": self.warm_seconds,
        }

    def _compile_buckets(self) -> int:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..ops import kernel as K

        cap = self.table_capacity
        pad_max = np.int32(np.iinfo(np.int32).max)
        # check_and_update_batch and update_batch donate their state:
        # thread ONE scratch table through every launch (its shape —
        # the jit cache key that must match serving — is (capacity+1,)
        # regardless of the hit bucket)
        state = K.make_table(cap)
        compiled = 0
        for H in self.warm_buckets:
            # an all-padding batch: slot C, delta 0, max INT32_MAX —
            # the exact inert row contract check_and_update_impl
            # documents, so warming mutates nothing
            slots = jnp.full((H,), cap, jnp.int32)
            zeros = jnp.zeros((H,), jnp.int32)
            maxes = jnp.full((H,), pad_max, jnp.int32)
            windows = jnp.ones((H,), jnp.int32)
            off = jnp.zeros((H,), bool)
            now = jnp.int32(0)
            state, result = K.check_and_update_batch(
                state, slots, zeros, maxes, windows, zeros, off, off,
                now,
            )
            jax.block_until_ready(result.admitted)  # noqa: warm-up helper — boot-time compile drain, never the decision path
            compiled += 1
            state = K.update_batch(
                state, slots, zeros, windows, off, off, now
            )
            jax.block_until_ready(state.values)  # noqa: warm-up helper — boot-time compile drain, never the decision path
            compiled += 1
        return compiled

    def stats(self) -> dict:
        """The ``standby_*`` family feed (merged into library_stats by
        the server wiring when ``--standby on``)."""
        return {
            "standby_ready": 1 if self.ready else 0,
            "standby_warm_kernels": self.warm_kernels,
            "standby_warm_seconds": self.warm_seconds,
        }

    def status(self) -> dict:
        """The ``GET /debug/pod/standby`` payload."""
        return {
            **self.stats(),
            "buckets": list(self.warm_buckets),
            "table_capacity": self.table_capacity,
            "host": self.coordinator.host_id,
            "topology_epoch": self.coordinator.router.topology_epoch,
            "join_ttfd_seconds": self.coordinator.join_ttfd_seconds,
        }
