"""Vendored gRPC server reflection (v1alpha) — SDK-free.

The reference serves reflection UNCONDITIONALLY from vendored file
descriptor sets (/root/reference/limitador-server/src/envoy_rls/
server.rs:232-236,254-263: tonic-reflection over the compiled
descriptor pool). grpcio-reflection is not installed in this image, so
— by the same standard as the vendored HTTP/2, HPACK and OTLP layers —
the protocol is implemented from scratch over the descriptor bytes the
checked-in ``server/proto`` modules already register in protobuf's
default descriptor pool:

 * ``ReflectionResponder`` — the pure request->response protocol logic
   (list_services, file_by_filename, file_containing_symbol,
   extension queries), shared by both servers;
 * ``make_reflection_handler`` — the grpc.aio stream_stream handler;
 * ``native_reflection_handler`` — the per-message handler the C++
   ingress drives through its bidi-stream surface
   (native/h2ingress.cc pump_stream_msgs / write_stream_msg).

``file_*`` responses carry each file's serialized FileDescriptorProto
plus its transitive imports (dependencies first), which is what lets
grpcurl-style clients rebuild the full schema from one query.
"""

from __future__ import annotations

from typing import Iterable, List

from .proto import reflection_pb2

__all__ = [
    "REFLECTION_SERVICE",
    "REFLECTION_METHOD",
    "make_sync_reflection_handler",
    "ReflectionResponder",
    "make_reflection_handler",
    "native_reflection_handler",
]

REFLECTION_SERVICE = "grpc.reflection.v1alpha.ServerReflection"
REFLECTION_METHOD = f"/{REFLECTION_SERVICE}/ServerReflectionInfo"

_NOT_FOUND = 5          # grpc NOT_FOUND
_INVALID_ARGUMENT = 3   # grpc INVALID_ARGUMENT


class ReflectionResponder:
    """Answers one ServerReflectionRequest at a time (the protocol is a
    bidi stream of independent request/response pairs)."""

    def __init__(self, service_names: Iterable[str], pool=None):
        from google.protobuf import descriptor_pool

        # The kuadrant service's descriptor registers on module import;
        # the envoy ones load with the proto package itself.
        from .proto.kuadrant.service.ratelimit.v1 import (  # noqa: F401
            rls_pb2 as _kuadrant_rls_pb2,
        )

        self._services: List[str] = sorted(
            set(service_names) | {REFLECTION_SERVICE}
        )
        self._pool = pool or descriptor_pool.Default()

    # -- internals ---------------------------------------------------------

    def _file_with_deps(self, fd) -> List[bytes]:
        """Serialized FileDescriptorProto of ``fd`` plus transitive
        imports, dependencies first (clients register in order)."""
        out: List[bytes] = []
        seen: set = set()

        def walk(f) -> None:
            if f.name in seen:
                return
            seen.add(f.name)
            for dep in f.dependencies:
                walk(dep)
            out.append(f.serialized_pb)

        walk(fd)
        return out

    def _find_file_for_symbol(self, symbol: str):
        """The python pool resolves messages/services/enums but not
        method or field full names; retry enclosing scopes so
        "pkg.Service.Method" (what grpcurl sends when describing a
        method) lands on the service's file."""
        parts = symbol.split(".")
        while parts:
            try:
                return self._pool.FindFileContainingSymbol(".".join(parts))
            except KeyError:
                parts.pop()
        raise KeyError(symbol)

    # -- protocol ----------------------------------------------------------

    def answer(self, request) -> "reflection_pb2.ServerReflectionResponse":
        resp = reflection_pb2.ServerReflectionResponse(
            valid_host=request.host
        )
        resp.original_request.CopyFrom(request)
        which = request.WhichOneof("message_request")
        try:
            if which == "list_services":
                for name in self._services:
                    resp.list_services_response.service.add(name=name)
            elif which == "file_by_filename":
                fd = self._pool.FindFileByName(request.file_by_filename)
                resp.file_descriptor_response.file_descriptor_proto.extend(
                    self._file_with_deps(fd)
                )
            elif which == "file_containing_symbol":
                fd = self._find_file_for_symbol(
                    request.file_containing_symbol
                )
                resp.file_descriptor_response.file_descriptor_proto.extend(
                    self._file_with_deps(fd)
                )
            elif which == "file_containing_extension":
                ext = request.file_containing_extension
                fd = self._pool.FindExtensionByNumber(
                    self._pool.FindMessageTypeByName(ext.containing_type),
                    ext.extension_number,
                ).file
                resp.file_descriptor_response.file_descriptor_proto.extend(
                    self._file_with_deps(fd)
                )
            elif which == "all_extension_numbers_of_type":
                name = request.all_extension_numbers_of_type
                desc = self._pool.FindMessageTypeByName(name)  # raises if absent
                numbers = resp.all_extension_numbers_response
                numbers.base_type_name = name
                numbers.extension_number.extend(
                    sorted(
                        e.number
                        for e in self._pool.FindAllExtensions(desc)
                    )
                )
            else:
                resp.error_response.error_code = _INVALID_ARGUMENT
                resp.error_response.error_message = (
                    "no known message_request set"
                )
        except KeyError:
            resp.error_response.error_code = _NOT_FOUND
            resp.error_response.error_message = "symbol or file not found"
        return resp


def make_reflection_handler(service_names: Iterable[str]):
    """grpc.aio generic handler serving ServerReflectionInfo."""
    import grpc

    responder = ReflectionResponder(service_names)

    async def server_reflection_info(request_iterator, context):
        async for request in request_iterator:
            yield responder.answer(request)

    return grpc.method_handlers_generic_handler(
        REFLECTION_SERVICE,
        {
            "ServerReflectionInfo": grpc.stream_stream_rpc_method_handler(
                server_reflection_info,
                request_deserializer=(
                    reflection_pb2.ServerReflectionRequest.FromString
                ),
                response_serializer=lambda m: m.SerializeToString(),
            )
        },
    )


def make_sync_reflection_handler(service_names: Iterable[str]):
    """Sync-server variant (the serving shards run sync gRPC servers:
    grpc.aio's completion-queue poller is process-global and unsafe
    across event loops)."""
    import grpc

    responder = ReflectionResponder(service_names)

    def server_reflection_info(request_iterator, context):
        for request in request_iterator:
            yield responder.answer(request)

    return grpc.method_handlers_generic_handler(
        REFLECTION_SERVICE,
        {
            "ServerReflectionInfo": grpc.stream_stream_rpc_method_handler(
                server_reflection_info,
                request_deserializer=(
                    reflection_pb2.ServerReflectionRequest.FromString
                ),
                response_serializer=lambda m: m.SerializeToString(),
            )
        },
    )


def native_reflection_handler(service_names: Iterable[str]):
    """Per-message handler for the C++ ingress's bidi-stream surface:
    each stream message answers with exactly one serialized response."""
    responder = ReflectionResponder(service_names)

    async def handler(blob: bytes) -> bytes:
        request = reflection_pb2.ServerReflectionRequest.FromString(blob)
        return responder.answer(request).SerializeToString()

    return handler
