"""Request-id propagation.

Mirrors the reference's RequestIdMiddleware (envoy_rls/server.rs:274-300,
http_api/server.rs:297-314): every request carries an ``x-request-id`` —
the client's if present, else a fresh uuid — echoed on HTTP responses and
gRPC initial metadata so logs and traces correlate across hops.
"""

from __future__ import annotations

import uuid

import grpc
from aiohttp import web

__all__ = ["http_request_id_middleware", "GrpcRequestIdInterceptor"]

HEADER = "x-request-id"


@web.middleware
async def http_request_id_middleware(request: web.Request, handler):
    request_id = request.headers.get(HEADER) or uuid.uuid4().hex
    request["request_id"] = request_id
    try:
        response = await handler(request)
    except web.HTTPException as exc:
        # Error responses (404/405/...) need the id most — stamp and re-raise.
        exc.headers[HEADER] = request_id
        raise
    response.headers[HEADER] = request_id
    return response


class GrpcRequestIdInterceptor(grpc.aio.ServerInterceptor):
    async def intercept_service(self, continuation, handler_call_details):
        metadata = dict(handler_call_details.invocation_metadata or ())
        request_id = metadata.get(HEADER) or uuid.uuid4().hex
        handler = await continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler

        inner = handler.unary_unary

        async def wrapped(request, context):
            await context.send_initial_metadata(((HEADER, request_id),))
            return await inner(request, context)

        return grpc.unary_unary_rpc_method_handler(
            wrapped,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
