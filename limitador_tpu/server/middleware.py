"""Request-id propagation.

Mirrors the reference's RequestIdMiddleware (envoy_rls/server.rs:274-300,
http_api/server.rs:297-314): every request carries an ``x-request-id`` —
the client's if present, else a fresh uuid — echoed on HTTP responses and
gRPC initial metadata so logs and traces correlate across hops. The id is
also published to the device-plane contextvar
(observability/device_plane.py) so flight-recorder entries for slow
decisions correlate with access logs without threading an argument
through every storage layer.
"""

from __future__ import annotations

import uuid

import grpc
from aiohttp import web

from ..observability.device_plane import set_request_id
from ..observability.tracing import adopt_traceparent

__all__ = ["http_request_id_middleware", "GrpcRequestIdInterceptor"]

HEADER = "x-request-id"
TRACEPARENT = "traceparent"


@web.middleware
async def http_request_id_middleware(request: web.Request, handler):
    request_id = request.headers.get(HEADER) or uuid.uuid4().hex
    request["request_id"] = request_id
    set_request_id(request_id)
    # Adopt the caller's W3C trace id (ISSUE 16): flight-recorder and
    # Prometheus exemplars then correlate with the caller's trace even
    # when no local exporter is configured.
    adopt_traceparent(request.headers.get(TRACEPARENT))
    try:
        response = await handler(request)
    except web.HTTPException as exc:
        # Error responses (404/405/...) need the id most — stamp and re-raise.
        exc.headers[HEADER] = request_id
        raise
    response.headers[HEADER] = request_id
    return response


class GrpcRequestIdInterceptor(grpc.aio.ServerInterceptor):
    """Echo (or mint) ``x-request-id`` on every RPC's initial metadata.

    All four handler kinds are wrapped — unary-unary (the RLS hot path)
    AND the streaming shapes (server reflection is stream-stream), which
    previously passed through silently with no id echo."""

    async def intercept_service(self, continuation, handler_call_details):
        metadata = dict(handler_call_details.invocation_metadata or ())
        request_id = metadata.get(HEADER) or uuid.uuid4().hex
        handler = await continuation(handler_call_details)
        if handler is None:
            return handler

        def _prelude(context):
            # Also publish to the device-plane contextvar: the wrapped
            # coroutine runs in the request's context, so the batcher's
            # flight recorder sees this id for decisions it coalesces.
            set_request_id(request_id)
            adopt_traceparent(metadata.get(TRACEPARENT))
            return context.send_initial_metadata(((HEADER, request_id),))

        for attr, factory, streams_out in (
            ("unary_unary", grpc.unary_unary_rpc_method_handler, False),
            ("unary_stream", grpc.unary_stream_rpc_method_handler, True),
            ("stream_unary", grpc.stream_unary_rpc_method_handler, False),
            ("stream_stream", grpc.stream_stream_rpc_method_handler, True),
        ):
            inner = getattr(handler, attr)
            if inner is None:
                continue
            if streams_out:

                async def wrapped(request, context, _inner=inner):
                    await _prelude(context)
                    async for response in _inner(request, context):
                        yield response

            else:

                async def wrapped(request, context, _inner=inner):
                    await _prelude(context)
                    return await _inner(request, context)

            return factory(
                wrapped,
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        return handler
