"""Generated protobuf bindings (wire-compatible Envoy RLS v3 + Kuadrant v1).

protoc emits absolute imports rooted at the proto path, so this package dir
joins sys.path before the generated modules load.
"""

import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
if _here not in sys.path:
    sys.path.insert(0, _here)

from envoy.service.ratelimit.v3 import rls_pb2  # noqa: E402
from envoy.config.core.v3 import base_pb2  # noqa: E402
from envoy.extensions.common.ratelimit.v3 import ratelimit_pb2  # noqa: E402
# Proto package grpc.reflection.v1alpha lives under a non-colliding module
# dir (the real `grpc` package would shadow a grpc/ tree).
from reflection_v1alpha import reflection_pb2  # noqa: E402

__all__ = ["rls_pb2", "base_pb2", "ratelimit_pb2", "reflection_pb2"]
