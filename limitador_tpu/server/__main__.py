"""limitador-tpu server binary.

CLI/env layering mirrors /root/reference/limitador-server/src/main.rs
(clap subcommands per storage, main.rs:483-730) and config.rs's env
registry; env vars keep the reference's names (LIMITS_FILE,
ENVOY_RLS_HOST/PORT, HTTP_API_HOST/PORT, RATE_LIMIT_HEADERS,
LIMIT_NAME_IN_PROMETHEUS_LABELS). CLI wins over env, env over defaults
(doc/server/configuration.md:46).

    python -m limitador_tpu.server LIMITS_FILE [storage] [options]

Storages: tpu (default — device-resident counters), memory, disk,
distributed. ``--validate`` parses the limits file and exits.
"""

from __future__ import annotations

import argparse
import asyncio
import inspect
import json
import logging
import os
import signal
import sys

from ..core.cel import CelError
from ..core.limiter import AsyncRateLimiter, RateLimiter
from ..observability.metrics import PrometheusMetrics
from .http_api import run_http_server
from .limits_file import LimitsFileError, LimitsFileWatcher, load_limits_file
from .rls import (
    RATE_LIMIT_HEADERS_DRAFT03,
    RATE_LIMIT_HEADERS_NONE,
    serve_rls,
)

__all__ = ["main", "build_parser"]

log = logging.getLogger("limitador")


class _JsonFormatter(logging.Formatter):
    """Structured JSON log lines, shaped like the reference's
    tracing_subscriber json layer (main.rs:922-957): timestamp, level,
    target, fields.message."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "timestamp": self.formatTime(record, "%Y-%m-%dT%H:%M:%S%z"),
            "level": record.levelname,
            "target": record.name,
            "fields": {"message": record.getMessage()},
        }
        if record.exc_info:
            entry["fields"]["exception"] = self.formatException(
                record.exc_info
            )
        return json.dumps(entry)


_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


def _setup_logging(structured: bool, level: str) -> None:
    handler = logging.StreamHandler(sys.stderr)
    if structured:
        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: "
                              "%(message)s")
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(_LEVELS.get(level.lower(), logging.INFO))


def _env(name, default=None):
    return os.environ.get(name, default)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="limitador-tpu-server",
        description="TPU-native rate limiter (Envoy RLS v3 + HTTP API)",
    )
    p.add_argument(
        "limits_file",
        nargs="?",
        default=_env("LIMITS_FILE"),
        help="YAML limits file (env: LIMITS_FILE)",
    )
    p.add_argument(
        "storage",
        nargs="?",
        default=_env("STORAGE", "tpu"),
        choices=["tpu", "sharded", "memory", "disk", "distributed", "cached"],
        help="counter storage backend (default: tpu); 'cached' is the "
        "write-behind topology over a disk authority (--disk-path); "
        "'sharded' splits the counter table over every visible device "
        "(keys routed by hash, global namespaces psum-replicated)",
    )
    p.add_argument("--rls-host", default=_env("ENVOY_RLS_HOST", "0.0.0.0"))
    p.add_argument(
        "--rls-port", type=int, default=int(_env("ENVOY_RLS_PORT", "8081"))
    )
    p.add_argument("--http-host", default=_env("HTTP_API_HOST", "0.0.0.0"))
    p.add_argument(
        "--http-port", type=int, default=int(_env("HTTP_API_PORT", "8080"))
    )
    p.add_argument(
        "--limit-name-in-labels",
        action="store_true",
        default=_env("LIMIT_NAME_IN_PROMETHEUS_LABELS") == "1",
        help="add limit names to prometheus labels",
    )
    p.add_argument(
        "--tracing-endpoint",
        default=_env("TRACING_ENDPOINT"),
        help="OTLP endpoint for span export (uses opentelemetry-sdk when "
        "installed, else the vendored OTLP/HTTP+JSON pipeline)",
    )
    p.add_argument(
        "--metric-labels",
        default=_env("METRIC_LABELS"),
        help="CEL map literal evaluated per request for extra prometheus "
        "labels, e.g. \"{'tenant': descriptors[0].tenant}\"",
    )
    p.add_argument(
        "--metric-labels-file",
        default=_env("METRIC_LABELS_FILE"),
        help="file holding the CEL label map; watched and hot-reloaded "
        "(label NAMES are fixed at startup, value expressions may change)",
    )
    p.add_argument(
        "--grpc-reflection-service",
        action="store_true",
        help="enable gRPC server reflection (requires grpcio-reflection)",
    )
    p.add_argument(
        "--rate-limit-headers",
        choices=[RATE_LIMIT_HEADERS_NONE, RATE_LIMIT_HEADERS_DRAFT03],
        default=_env("RATE_LIMIT_HEADERS", RATE_LIMIT_HEADERS_NONE),
    )
    p.add_argument(
        "--validate",
        action="store_true",
        help="validate the limits file and exit",
    )
    p.add_argument(
        "--structured-logs",
        action="store_true",
        default=_env("STRUCTURED_LOGS", "") == "1",
        help="emit structured JSON log lines (main.rs:577-580)",
    )
    p.add_argument(
        "--log-level",
        default=_env("LIMITADOR_LOG", _env("RUST_LOG", "info")),
        help="log level: trace|debug|info|warn|error",
    )
    def _positive_interval(value: str) -> float:
        interval = float(value)
        if interval <= 0:
            raise argparse.ArgumentTypeError(
                "poll interval must be > 0 seconds"
            )
        return interval

    p.add_argument(
        "--limits-poll-interval", type=_positive_interval,
        default=_positive_interval(_env("LIMITS_FILE_POLL_INTERVAL", "1.0")),
        help="limits/labels file change-poll interval in seconds, > 0 "
        "(the reference watches via inotify, main.rs limits_file "
        "watcher; polling is filesystem-agnostic — ConfigMap symlink "
        "swaps included)",
    )
    # storage tuning
    p.add_argument(
        "--cache-size", type=int, default=None,
        help="qualified-counter cache cap (memory/tpu)",
    )
    p.add_argument(
        "--tpu-capacity", type=int,
        default=int(_env("TPU_TABLE_CAPACITY", str(1 << 20))),
        help="device counter-table capacity (tpu)",
    )
    p.add_argument(
        "--batch-delay-us", type=int,
        default=int(_env("TPU_BATCH_DELAY_US", "500")),
        help="micro-batcher linger in microseconds (tpu)",
    )

    def _dispatch_chunk(value: str):
        if value in ("auto", ""):
            return None  # auto-tuned from the queue-wait signal
        if value in ("off", "0"):
            return 0  # monolithic dispatch
        chunk = int(value)
        if chunk < 0:
            raise argparse.ArgumentTypeError(
                "dispatch chunk must be >= 0, 'off' or 'auto'"
            )
        return chunk

    p.add_argument(
        "--dispatch-chunk", type=_dispatch_chunk,
        default=_dispatch_chunk(_env("TPU_DISPATCH_CHUNK", "auto")),
        help="tpu: hits per pipelined sub-batch launch — a flush splits "
        "into overlapping chunks so a request's device round trip is its "
        "chunk's, not the whole batch's (docs/configuration.md). "
        "'auto' (default) sizes chunks from the device-plane queue-wait "
        "signal against the 2ms latency budget; 'off'/0 dispatches "
        "monolithically; N pins the chunk size",
    )
    p.add_argument(
        "--pipeline",
        choices=["standard", "compiled", "native"],
        default=_env("TPU_PIPELINE", "standard"),
        help="tpu request path: per-request CEL (standard), batch-compiled "
        "vectorized masks (compiled), or the C++ columnar host path for "
        "ShouldRateLimit (native; falls back to compiled when the native "
        "library is unavailable)",
    )
    p.add_argument(
        "--serving-shards", type=int,
        default=int(_env("SERVING_SHARDS", "1")),
        help="number of RLS gRPC serving loops: each extra shard is a "
        "thread with its own event loop and its own server on the SAME "
        "port (SO_REUSEPORT), all feeding the shared device lane — "
        "accept/parse/future-resolution parallelize across cores "
        "(requires a batched tpu storage to pay off; 1 = single loop)",
    )
    p.add_argument(
        "--plan-cache-size", type=int,
        default=int(_env("PLAN_CACHE_SIZE", str(1 << 16))),
        help="hot-descriptor decision-plan cache entries per pipeline "
        "(byte-identical repeat requests skip parse/CEL/slot hashing; "
        "epoch-invalidated on every limits change; 0 disables)",
    )
    p.add_argument(
        "--native-hot-lane",
        choices=["on", "off"],
        default=_env("TPU_NATIVE_HOT_LANE", "on"),
        help="zero-Python hot lane for the native pipeline: repeat "
        "descriptors run plan lookup, columnar staging and response "
        "build in one GIL-free C call (C-side mirror of the decision-"
        "plan cache; epoch/slot-coherent). 'off' pins the pure-Python "
        "cached lane — byte-identical decisions, host-bound throughput",
    )
    p.add_argument(
        "--lease-mode",
        choices=["on", "off"],
        default=_env("TPU_LEASE_MODE", "off"),
        help="quota-leasing edge tier (requires --pipeline native with "
        "the hot lane): hot descriptors get pre-debited token batches "
        "attached to their mirrored plans, so repeat decisions complete "
        "with zero device work; over-admission per counter is bounded "
        "by its outstanding leased tokens, grants never exceed the "
        "remaining window headroom, and cold/exact-path keys stay "
        "exact. 'off' (default) is byte-identical to the pre-lease "
        "serving path",
    )
    p.add_argument(
        "--lease-max-tokens", type=int,
        default=int(_env("TPU_LEASE_MAX_TOKENS", "1024")),
        help="per-lease token cap (the broker sizes each grant from "
        "observed demand up to this, doubling on renewal and halving "
        "on a headroom denial)",
    )
    p.add_argument(
        "--native-ingress",
        action="store_true",
        default=_env("TPU_NATIVE_INGRESS", "") == "1",
        help="serve ShouldRateLimit through the vendored C++ HTTP/2 "
        "ingress on --rls-port (requires tpu storage, --pipeline native, "
        "headers NONE); the Python gRPC server (Kuadrant + Envoy with "
        "headers) moves to --rls-port + 1",
    )
    # pod-scale serving (docs/configuration.md "Pod-scale serving"):
    # jax.distributed global mesh + shard-aware routed ingress
    p.add_argument(
        "--pod-coordinator", default=_env("TPU_POD_COORDINATOR"),
        help="pod: jax.distributed coordinator address (host:port); "
        "required when --pod-processes > 1. Process 0 must be reachable "
        "there before the others start",
    )
    p.add_argument(
        "--pod-processes", type=int,
        default=int(_env("TPU_POD_PROCESSES", "1")),
        help="pod: total number of pod processes (hosts); 1 = no pod "
        "(the default single-host topology)",
    )
    p.add_argument(
        "--pod-process-id", type=int,
        default=int(_env("TPU_POD_PROCESS_ID", "0")),
        help="pod: this process's id in [0, --pod-processes)",
    )
    p.add_argument(
        "--pod-peer", action="append", default=None,
        help="pod: peer-lane address of each pod process in process-id "
        "order, repeatable (env TPU_POD_PEERS, comma separated); a "
        "descriptor owned by another host is forwarded once over this "
        "lane",
    )
    p.add_argument(
        "--pod-peer-listen", default=_env("TPU_POD_PEER_LISTEN"),
        help="pod: bind address of this host's peer lane "
        "(default 0.0.0.0:<rls-port + 2>)",
    )
    # pod resilience plane (docs/configuration.md "Pod resilience"):
    # peer health + retry/hedge on the lane, degraded-owner failover
    # with journaled reconcile behind a per-peer breaker
    p.add_argument(
        "--pod-degraded-mode", choices=["on", "off"],
        default=_env("TPU_POD_DEGRADED_MODE", "on"),
        help="pod: on (default) = forward failures feed a per-peer "
        "breaker and fail over to a local exact stand-in that journals "
        "deltas for replay on recovery (plus one jittered retry for "
        "suspect peers); off = PR 10 behavior, a peer failure fails "
        "that request (UNAVAILABLE/500)",
    )
    p.add_argument(
        "--pod-hedge-ms", type=float,
        default=float(_env("TPU_POD_HEDGE_MS", "0")),
        help="pod: >0 enables hedged forwards — when an in-flight "
        "forward outlasts max(this floor, the tracked peer p99) a "
        "second attempt races it on a fresh channel; 0 (default) "
        "disables hedging",
    )
    p.add_argument(
        "--pod-peer-breaker-failures", type=int,
        default=int(_env("TPU_POD_PEER_BREAKER_FAILURES", "3")),
        help="pod: consecutive forward failures that open a peer's "
        "failover breaker",
    )
    p.add_argument(
        "--pod-peer-breaker-reset-ms", type=float,
        default=float(_env("TPU_POD_PEER_BREAKER_RESET_MS", "2000")),
        help="pod: ms an open peer breaker dwells before recovery "
        "probes may close it",
    )
    # pod observability plane (docs/observability.md, ISSUE 12)
    p.add_argument(
        "--pod-events", type=int,
        default=int(_env("TPU_POD_EVENTS", "512")),
        help="pod: capacity of the typed pod event ring served at "
        "GET /debug/events (per-kind counts export as "
        "pod_events_total regardless of ring size)",
    )
    # elastic pod (docs/configuration.md "Elastic pod", ISSUE 15):
    # live resharding + membership change on a running pod
    p.add_argument(
        "--pod-resize", choices=["on", "off"],
        default=_env("TPU_POD_RESIZE", "off"),
        help="pod: on = arm the elastic-membership plane — forwards "
        "stamp the topology epoch (wrong-epoch forwards are rejected "
        "rerouteable), the migrate/resize lane kinds serve, and "
        "POST /debug/pod/resize drives a live resize/add_host/"
        "drain_host with slice-by-slice migration and zero lost "
        "updates (an abort reverts to the old topology). off "
        "(default) = byte-identical PR 14 wire format and behavior",
    )
    # warm standby & fast join (docs/configuration.md "Warm standby &
    # fast join", ISSUE 18)
    p.add_argument(
        "--standby", choices=["on", "off"],
        default=_env("TPU_POD_STANDBY", "off"),
        help="pod: on = boot as a warm standby — form the host-local "
        "mesh, pre-compile the pow2 hit-bucket decision kernels, serve "
        "the peer lane, and wait for a coordinator's join_admin adopt "
        "(POST /debug/pod/join on any member promotes this host in "
        "under a second). Requires --pod-resize on wiring; off "
        "(default) = byte-identical PR 17 construction and wire "
        "format",
    )
    p.add_argument(
        "--xla-cache-dir", default=_env("TPU_XLA_CACHE_DIR", ""),
        help="persistent XLA compilation cache directory "
        "(jax.config.jax_compilation_cache_dir): compiled programs "
        "survive process restarts, so a warm standby — or ANY "
        "restarting host — skips recompiling kernels it has compiled "
        "before; empty (default) = in-memory jit cache only",
    )
    # tiered storage (docs/configuration.md "Tiered storage", ISSUE 17):
    # device-resident hot set over an exact host cold tier
    p.add_argument(
        "--tier-mode", choices=["on", "off"],
        default=_env("TPU_TIER_MODE", "off"),
        help="tpu: on = tiered counter storage — the device table "
        "serves the resident hot set, LRU evictions demote their exact "
        "cell (value + remaining window) to a host cold tier instead "
        "of dropping it, cold keys decide exactly on the host, and a "
        "TierManager thread migrates counters on observed heat priced "
        "against the fitted serving model (plain tpu storage only; "
        "GET /debug/tiering serves the live state). off (default) = "
        "byte-identical single-tier behavior",
    )
    p.add_argument(
        "--tier-cold", default=_env("TPU_TIER_COLD", ""),
        help="tiered: path of the cold tier's append-log disk spill "
        "(JSON lines, absolute cell state, last-row-wins; empty = "
        "no disk spill)",
    )
    p.add_argument(
        "--tier-migrate-interval", type=float,
        default=float(_env("TPU_TIER_MIGRATE_INTERVAL", "2.0")),
        help="tiered: seconds between TierManager migration rounds "
        "(each round drains the heat accumulators, prices candidates "
        "and runs the two-phase ledgered moves)",
    )
    # capacity controller (docs/configuration.md "Self-driving
    # capacity", ISSUE 20): one model-based loop over admission,
    # shedding, chunking, lease sizing AND pod membership
    p.add_argument(
        "--capacity-controller", choices=["on", "off", "observe"],
        default=_env("TPU_CTL_MODE", "off"),
        help="self-driving capacity (ISSUE 20): one model-based "
        "controller jointly actuates the admission AIMD ceiling, the "
        "deadline-shed priority floor, the ChunkPlanner target, the "
        "lease grant scale and pod membership (warm-standby join on "
        "sustained burn, tail-host drain on sustained idle). observe "
        "= compute and log every decision without actuating; off "
        "(default) = controller not constructed, byte-identical "
        "PR 18 behavior",
    )
    p.add_argument(
        "--ctl-interval", type=float,
        default=float(_env("TPU_CTL_INTERVAL_S", "1.0")),
        help="controller: seconds between control ticks",
    )
    p.add_argument(
        "--ctl-sustain", type=float,
        default=float(_env("TPU_CTL_SUSTAIN_S", "5.0")),
        help="controller: a membership proposal must hold its "
        "hysteresis band this long before actuating (leaving the "
        "band resets the clock)",
    )
    p.add_argument(
        "--ctl-dwell", type=float,
        default=float(_env("TPU_CTL_DWELL_S", "30.0")),
        help="controller: minimum seconds between membership "
        "actuations (with --ctl-sustain, what keeps diurnal ramps "
        "from flapping topology)",
    )
    p.add_argument(
        "--ctl-standby", default=_env("TPU_CTL_STANDBY", ""),
        help="controller: comma-separated peer-lane addresses of warm "
        "standbys (--standby on processes) the controller may promote "
        "on sustained burn; empty = membership grows unavailable",
    )
    p.add_argument(
        "--ctl-min-hosts", type=int,
        default=int(_env("TPU_CTL_MIN_HOSTS", "1")),
        help="controller: never drain the pod below this many hosts",
    )
    p.add_argument(
        "--ctl-max-hosts", type=int,
        default=int(_env("TPU_CTL_MAX_HOSTS", "8")),
        help="controller: never grow the pod above this many hosts",
    )
    p.add_argument(
        "--ctl-grow-headroom", type=float,
        default=float(_env("TPU_CTL_GROW_HEADROOM", "1.2")),
        help="controller: propose add_host while the model's capacity "
        "headroom ratio stays below this band",
    )
    p.add_argument(
        "--ctl-shrink-headroom", type=float,
        default=float(_env("TPU_CTL_SHRINK_HEADROOM", "3.0")),
        help="controller: propose drain_host while the headroom ratio "
        "stays above this band (the dead band between the two absorbs "
        "ramps)",
    )
    # pod fast path (docs/configuration.md "Pod fast path", ISSUE 13):
    # shard-aware native hot lane + lockstep psum lane for global limits
    p.add_argument(
        "--pod-psum-lane", choices=["on", "off"],
        default=_env("TPU_POD_PSUM_LANE", "off"),
        help="pod: on = fixed-window --global-namespaces limits are "
        "decided LOCALLY on every host against lockstep-exchanged "
        "remote partials (pod-wide psum) instead of pinning the whole "
        "namespace to one host; trades bounded over-admission (one "
        "exchange interval per remote host, like the reference's "
        "cached-Redis mode) for routed-share -> 1 on those namespaces. "
        "off (default) = exact namespace pinning. Every pod host must "
        "agree on this flag (the exchange is collective)",
    )
    p.add_argument(
        "--pod-psum-interval-ms", type=float,
        default=float(_env("TPU_POD_PSUM_INTERVAL_MS", "250")),
        help="pod: pacing of the lockstep psum exchange rounds (also "
        "the over-admission bound's time constant)",
    )
    p.add_argument(
        "--global-namespaces", default=_env("GLOBAL_NAMESPACES"),
        help="sharded: comma-separated namespaces whose counters are "
        "psum-replicated across shards (one budget mesh-wide)",
    )
    p.add_argument(
        "--global-region", type=int,
        default=int(_env("GLOBAL_REGION", "1024")),
        help="sharded: per-shard slots reserved for global counters",
    )
    p.add_argument(
        "--authority-listen", default=_env("AUTHORITY_LISTEN"),
        help="serve this process's counter storage as a shared authority "
        "for remote write-behind replicas (the out-of-process Redis role), "
        "e.g. 0.0.0.0:5101",
    )
    p.add_argument(
        "--authority-url", default=_env("AUTHORITY_URL"),
        help="cached: flush write-behind deltas to a remote authority "
        "(host:port of another server's --authority-listen) instead of a "
        "local disk store",
    )
    p.add_argument(
        "--batch-size", type=int,
        default=int(_env("REDIS_LOCAL_CACHE_BATCH_SIZE", "100")),
        help="cached: max deltas per authority flush (main.rs:651-658; "
        "default 100, redis/mod.rs:10-13)",
    )
    p.add_argument(
        "--flush-period", type=float,
        default=float(_env("REDIS_LOCAL_CACHE_FLUSHING_PERIOD_MS", "1000")),
        help="cached: write-behind flush period in MILLISECONDS, same "
        "unit as the flag's env var and the reference CLI "
        "(main.rs:664-674; default 1000)",
    )
    p.add_argument(
        "--max-cached", type=int, default=int(_env("MAX_CACHED", "10000")),
        help="cached: max locally cached counters (default 10000)",
    )
    p.add_argument(
        "--response-timeout", type=float,
        default=float(_env("RESPONSE_TIMEOUT", "350")),
        help="cached: remote-authority response timeout in MILLISECONDS "
        "(main.rs:684-691; default 350, redis/mod.rs:13); applies with "
        "--authority-url",
    )
    p.add_argument("--disk-path", default=_env("DISK_PATH"))
    p.add_argument(
        "--snapshot-path", default=_env("TPU_SNAPSHOT_PATH"),
        help="tpu: periodically checkpoint the counter table here and "
        "restore from it on startup",
    )
    p.add_argument(
        "--snapshot-period", type=float,
        default=float(_env("TPU_SNAPSHOT_PERIOD", "30")),
        help="tpu: seconds between counter-table checkpoints",
    )
    p.add_argument(
        "--peer", action="append", default=None,
        help="distributed/tpu: peer replication address (repeatable; with "
        "tpu storage this enables the replicated device-table topology)",
    )
    p.add_argument("--node-id", default=_env("NODE_ID"))
    p.add_argument(
        "--listen-address", default=_env("LISTEN_ADDRESS"),
        help="distributed: replication listen address",
    )
    p.add_argument(
        "--advertise-address", default=_env("ADVERTISE_ADDRESS"),
        help="distributed/tpu: address advertised to peers in gossip "
        "Hello/Membership packets (defaults to --listen-address; set it "
        "when binding 0.0.0.0 — e.g. the pod's stable DNS name — so "
        "peers learn a dialable URL)",
    )
    # admission plane (admission/controller.py)
    p.add_argument(
        "--admission-mode",
        choices=["off", "monitor", "enforce"],
        default=_env("ADMISSION_MODE", "off"),
        help="admission plane: off (default), monitor (breaker/failover "
        "active, sheds counted but not enforced), enforce (deadline/"
        "overload sheds enforced); requires a batched tpu storage",
    )
    p.add_argument(
        "--breaker-failures", type=int,
        default=int(_env("BREAKER_FAILURES", "3")),
        help="consecutive device-batch failures that open the "
        "device-plane circuit breaker",
    )
    p.add_argument(
        "--breaker-stall-ms", type=float,
        default=float(_env("BREAKER_STALL_MS", "2000")),
        help="an in-flight device batch older than this trips the "
        "breaker (the hung-device_sync failure mode)",
    )
    p.add_argument(
        "--breaker-reset-ms", type=float,
        default=float(_env("BREAKER_RESET_MS", "5000")),
        help="open-state dwell before a half-open device probe",
    )
    p.add_argument(
        "--max-inflight", type=int,
        default=int(_env("ADMISSION_MAX_INFLIGHT", "4096")),
        help="hard ceiling of the adaptive (AIMD) concurrency limit",
    )
    p.add_argument(
        "--admission-target-queue-ms", type=float,
        default=float(_env("ADMISSION_TARGET_QUEUE_MS", "20")),
        help="queue-wait target the AIMD limit steers toward; also the "
        "basis of deadline-aware shedding",
    )
    p.add_argument(
        "--shed-response",
        choices=["unavailable", "overlimit"],
        default=_env("SHED_RESPONSE", "unavailable"),
        help="RLS semantics of a shed: unavailable (gRPC UNAVAILABLE / "
        "HTTP 503, Envoy failure-mode decides) or overlimit "
        "(OVER_LIMIT / 429)",
    )
    p.add_argument(
        "--priority-key", default=_env("PRIORITY_KEY", "priority"),
        help="descriptor entry key carrying a request's priority class "
        "(low|normal|high|critical)",
    )
    p.add_argument(
        "--priority", action="append", default=None,
        help="namespace priority mapping NS=CLASS (repeatable); limits-"
        "file `priority:` annotations and the descriptor entry override "
        "per request",
    )
    p.add_argument(
        "--profile-dir",
        default=_env("TPU_PROFILE_DIR", "/tmp/limitador-tpu-profile"),
        help="default directory for on-demand jax.profiler captures "
        "(POST /debug/profile can override per capture)",
    )
    p.add_argument(
        "--native-trace-sample", type=int,
        default=int(_env("TPU_NATIVE_TRACE_SAMPLE", "0")),
        help="sample 1 in N hot-lane batches with a native trace id so "
        "OTLP device_batch spans carry the C-side phase splits for "
        "zero-Python rows (0 = off, the default)",
    )
    p.add_argument(
        "--native-slow-row-us", type=float,
        default=float(_env("TPU_NATIVE_SLOW_ROW_US", "50")),
        help="slow-row exemplar threshold of the native telemetry "
        "plane: a hot-lane begin averaging more than this many "
        "microseconds per row records a native phase breakdown + "
        "descriptor digest into the flight recorder (0 disables "
        "exemplars; histograms stay on)",
    )
    p.add_argument(
        "--slo-budget-ms", type=float,
        default=float(_env("TPU_SLO_BUDGET_MS", "2.0")),
        help="decision-latency SLO budget the burn-rate watchdog "
        "tracks at p99 over 5m/1h windows (slo_* gauges, /debug/stats "
        "slo section)",
    )
    p.add_argument(
        "--usage-topk", type=int,
        default=int(_env("TPU_USAGE_TOPK", "64")),
        help="heavy-hitter slots drained per pass by the tenant usage "
        "observatory (GET /debug/top, tenant_* metrics; 0 disables the "
        "observatory)",
    )
    p.add_argument(
        "--usage-drain-interval", type=float,
        default=float(_env("TPU_USAGE_DRAIN_S", "1.0")),
        help="seconds between heavy-hitter accumulator drains (also "
        "the control-signal timeline tick)",
    )
    p.add_argument(
        "--usage-near-threshold", type=float,
        default=float(_env("TPU_USAGE_NEAR_THRESHOLD", "0.9")),
        help="value/max_value utilization at which a sampled counter "
        "counts as near-exhaustion (tenant_near_exhaustion gauge)",
    )
    p.add_argument(
        "--model-fit",
        choices=["on", "off"],
        default=_env("TPU_MODEL_FIT", "on"),
        help="online serving-model observatory (ISSUE 14): fit the "
        "serving-model coefficients from live launch telemetry "
        "(model_*/capacity_* gauges, GET /debug/capacity, the "
        "model_r2/capacity_headroom_ratio/model_drift ControlSignals "
        "tail). 'off' detaches the ingest tap entirely",
    )
    p.add_argument(
        "--flight",
        choices=["on", "off"],
        default=_env("TPU_FLIGHT", "on"),
        help="flight recorder (ISSUE 16): always-on sampled decision "
        "exemplars + worst-K tails per lane, trigger engine (SLO burn, "
        "breaker open, resize abort, drift, device-probe fall, manual "
        "POST /debug/flight/trigger) persisting pod-correlated "
        "incident bundles (GET /debug/flight)",
    )
    p.add_argument(
        "--flight-sample", type=int,
        default=int(_env("TPU_FLIGHT_SAMPLE", "64")),
        help="flight recorder exemplar sampling stride: 1 in N "
        "decisions rings a full stage breakdown (worst-K tails are "
        "kept regardless; 1 records every decision)",
    )
    p.add_argument(
        "--flight-spool-dir",
        default=_env("TPU_FLIGHT_SPOOL", "/tmp/limitador-flight"),
        help="retention-capped directory incident bundles persist to "
        "(self-contained JSON, served back at GET /debug/flight)",
    )
    p.add_argument(
        "--flight-window", type=float,
        default=float(_env("TPU_FLIGHT_WINDOW_S", "10.0")),
        help="seconds of exemplar/signal history a fired bundle "
        "freezes (also the window peers contribute over)",
    )
    p.add_argument(
        "--flight-profile-s", type=float,
        default=float(_env("TPU_FLIGHT_PROFILE_S", "0.0")),
        help="bounded jax.profiler capture attached to automatic "
        "trigger fires, in seconds (0 = off; manual triggers opt in "
        "per request)",
    )
    p.add_argument(
        "--tracing-sample-rate", type=float,
        default=float(_env("TRACING_SAMPLE_RATE", "1.0")),
        help="head-sampling rate for exported spans: 1.0 records "
        "every request (the default, current behavior), 0.01 one in "
        "a hundred; the datastore_latency aggregation is never "
        "sampled",
    )
    p.add_argument(
        "--metrics-exemplars",
        choices=["on", "off"],
        default=_env("TPU_METRICS_EXEMPLARS", "off"),
        help="attach trace-id exemplars to tail-bucket "
        "datastore-latency observations and render /metrics in the "
        "OpenMetrics exposition (the only format carrying exemplars); "
        "off keeps the text 0.0.4 exposition byte-identical",
    )
    return p


def _try_restore(path, restore_fn, what: str):
    """Restore-or-None with rejected-checkpoint preservation (shared by
    the tpu and sharded branches)."""
    if not (path and os.path.exists(path)):
        return None
    try:
        storage = restore_fn(path)
    except Exception as exc:
        log.warning(
            f"snapshot {path} unreadable ({exc}); starting with a fresh "
            f"{what}")
        _preserve_rejected_snapshot(path)
        return None
    log.info(f"restored {what} from {path}")
    return storage


def _seed_from_sibling_snapshots(storage, base, owned, total_shards):
    """Slice-mapped restore after a membership change (ISSUE 15): the
    exact checkpoint for this host's owned shard range does not exist,
    so decode every sibling checkpoint (current ``.shards<lo>-<hi>``
    names AND legacy ``.host<id>`` ones) and seed ONLY the counters
    this host owns under the CURRENT topology, through apply_deltas
    (fresh windows, exact spends — the failover-replay accuracy
    contract). Disjoint by construction: every host filters to its own
    contiguous range, so a pod-wide rolling restart re-homes each slice
    exactly once."""
    import glob

    from ..routing import counter_key, stable_hash
    from ..tpu.sharded import snapshot_items

    lo, hi = owned
    files = sorted(
        set(glob.glob(base + ".shards*") + glob.glob(base + ".host*"))
    )
    files = [
        f for f in files
        if not (f.endswith(".rejected") or f.endswith(".tmp"))
    ]
    # Newest checkpoint first, and each counter seeds from exactly ONE
    # file: a live counter can appear in several files (a legacy
    # .host<id> left behind next to the .shards name that replaced it,
    # or stale files from a previous shard range) and applying it per
    # file would double its spend.
    files.sort(key=lambda f: os.path.getmtime(f), reverse=True)
    seeded = 0
    seen = set()
    for path in files:
        try:
            items = snapshot_items(path)
        except Exception as exc:
            log.warning(
                f"pod: sibling snapshot {path} undecodable ({exc}); "
                "skipped")
            continue
        mine = []
        for counter, value in items:
            key = counter_key(counter)
            if key in seen:
                continue
            if lo <= stable_hash(key) % total_shards < hi:
                seen.add(key)
                mine.append((counter, value))
        if not mine:
            continue
        try:
            storage.apply_deltas(mine)
            seeded += len(mine)
        except Exception as exc:
            log.warning(f"pod: seeding from {path} failed: {exc}")
    if seeded:
        log.info(
            f"pod: slice-mapped restore seeded {seeded} owned "
            f"counters from {len(files)} sibling checkpoint(s)")


def _preserve_rejected_snapshot(path: str) -> None:
    """A checkpoint we could not restore must be moved aside, NOT left in
    place: the fresh table's periodic snapshot loop would overwrite it,
    destroying counters that a correctly-configured restart could still
    recover."""
    rejected = path + ".rejected"
    try:
        os.replace(path, rejected)
        log.warning(f"preserved rejected snapshot as {rejected}")
    except OSError as exc:
        log.warning(f"could not preserve rejected snapshot: {exc}")


def _pod_local_mesh():
    """Pod mode: the sharded storage shards over THIS host's devices
    only (the default mesh would span the whole pod and every launch
    would be an SPMD program all hosts must enter together); the
    cross-host partition of the key space lives in the routed frontend
    (server/peering.py), not in the device mesh. None single-host —
    the storage's default mesh is already right there."""
    import jax

    if jax.process_count() > 1:
        from ..parallel import make_host_mesh

        return make_host_mesh()
    return None


def _pod_native_capable(args, log) -> bool:
    """Pod-mode native-pipeline capability check (ISSUE 13): the
    shard-aware hot lane is the only native plane that classifies
    foreign-owned keys, so pod mode serves the native pipeline ONLY
    when that lane can come up — ``--native-hot-lane on`` AND a built
    library exporting both the lane and the pod ownership mirror.
    Anything less warns and falls back to the routed compiled plane,
    the same warn-and-fallback shape as ``--native-hot-lane`` itself
    (never a hard refusal, never a silently wrong fast path)."""
    from .. import native as native_mod

    if args.native_hot_lane != "on":
        log.warning(
            "pod mode: --native-hot-lane off leaves the native pipeline "
            "without the shard-aware lane; serving through the routed "
            "compiled pipeline")
        return False
    if not native_mod.available():
        log.warning(
            "pod mode: native hostpath library unavailable; serving "
            "through the routed compiled pipeline")
        return False
    if not native_mod.pod_available():
        log.warning(
            "pod mode: native library lacks the pod ownership exports "
            "(stale binary — rebuild native/hostpath.cc); serving "
            "through the routed compiled pipeline")
        return False
    if args.plan_cache_size <= 0:
        log.warning(
            "pod mode: --plan-cache-size 0 disables the plan mirror "
            "the shard-aware lane rides; serving through the routed "
            "compiled pipeline")
        return False
    if args.pod_processes - 1 > 127 - native_mod.LANE_FOREIGN_BASE:
        log.warning(
            f"pod mode: {args.pod_processes} hosts exceed the native "
            "lane's int8 owner encoding (max "
            f"{128 - native_mod.LANE_FOREIGN_BASE}); serving through "
            "the routed compiled pipeline")
        return False
    return True


async def _discard_pipeline(pipeline):
    """Dispose a constructed-but-unserved NativeRlsPipeline (pod-mode
    fallback): its __init__ already wired eviction hooks on the live
    storage table — left attached they would call into an abandoned
    native context on every slot release for the process lifetime —
    and started its thread pools. Returns None for assignment."""
    table = pipeline.storage._table
    table.on_native_release = None
    table.on_slot_release = None
    table.on_clear = None
    try:
        await pipeline.close()
    except Exception:
        pass  # a half-built pipeline must not fail the fallback boot
    return None


def _pin_platform() -> None:
    """Pin the jax backend per LIMITADOR_TPU_PLATFORM before anything
    initializes it. The axon site hook overrides the JAX_PLATFORMS env
    var, so this is the supported way to run the tpu storages on the
    host backend (accelerator-less validation, on-box serving
    measurements). Called before pod formation AND before the storage
    build — whichever runs first wins (idempotent)."""
    platform = os.environ.get("LIMITADOR_TPU_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)


def build_limiter(args, on_partitioned=None):
    """Limiter::new equivalent (main.rs:93-185): pick + build the backend.
    ``on_partitioned`` reaches storages that track authority partitions
    (the datastore_partitioned gauge)."""
    _pin_platform()
    if args.authority_url and args.storage != "cached":
        raise SystemExit(
            f"--authority-url only applies to the 'cached' storage "
            f"(got {args.storage!r}); run the replica as: "
            "... cached --authority-url HOST:PORT"
        )
    if args.storage == "memory":
        from ..storage.in_memory import DEFAULT_CACHE_SIZE, InMemoryStorage

        return RateLimiter(
            InMemoryStorage(args.cache_size or DEFAULT_CACHE_SIZE)
        )
    if args.storage == "tpu":
        from ..tpu.batcher import AsyncTpuStorage
        from ..tpu.storage import TpuStorage

        if args.peer or args.listen_address:
            # Replicated node: the constructor owns broker wiring, so the
            # checkpoint loads INTO the instance — restoring a plain
            # TpuStorage here would silently drop the node out of the
            # gossip mesh.
            from ..tpu.replicated import TpuReplicatedStorage

            storage = TpuReplicatedStorage(
                node_id=args.node_id or "node",
                listen_address=args.listen_address or "0.0.0.0:5001",
                advertise_address=args.advertise_address,
                peers=args.peer or [],
                capacity=args.tpu_capacity,
                cache_size=args.cache_size,
            )
            if args.snapshot_path and os.path.exists(args.snapshot_path):
                try:
                    storage.load_snapshot(args.snapshot_path)
                except Exception as exc:
                    log.warning(
                        f"snapshot {args.snapshot_path} unreadable "
                        f"({exc}); starting with a fresh replicated table")
                    _preserve_rejected_snapshot(args.snapshot_path)
                else:
                    log.info(
                        f"restored replicated counter table from "
                        f"{args.snapshot_path}")
        else:
            # Tiered storage (ISSUE 17): the facade is a TpuStorage, so
            # the whole fast path (plan cache, native hot lane, lease
            # tier) rides it unchanged; off (default) keeps the exact
            # single-tier construction below byte-identical.
            cls = TpuStorage
            if getattr(args, "tier_mode", "off") == "on":
                from ..tier import TieredStorage

                cls = TieredStorage
            storage = _try_restore(
                args.snapshot_path,
                lambda p: cls.restore(p, cache_size=args.cache_size),
                "counter table",
            )
            if storage is not None and storage._capacity != args.tpu_capacity:
                log.warning(
                    f"warning: snapshot capacity {storage._capacity} "
                    f"overrides --tpu-capacity {args.tpu_capacity}")
            if storage is None:
                if cls is TpuStorage:
                    storage = cls(
                        capacity=args.tpu_capacity,
                        cache_size=args.cache_size,
                    )
                else:
                    storage = cls(
                        capacity=args.tpu_capacity,
                        cache_size=args.cache_size,
                        spill_path=getattr(args, "tier_cold", "") or None,
                    )
            elif cls is not TpuStorage:
                # restore() has no spill knob; arm it post-restore
                storage._cold._spill_path = (
                    getattr(args, "tier_cold", "") or None
                )
        async_storage = AsyncTpuStorage(
            storage, max_delay=args.batch_delay_us / 1e6,
            dispatch_chunk=args.dispatch_chunk,
        )
        if args.pipeline in ("compiled", "native"):
            from ..tpu.pipeline import CompiledTpuLimiter

            return CompiledTpuLimiter(
                async_storage,
                plan_cache_size=getattr(args, "plan_cache_size", 1 << 16),
                dispatch_chunk=args.dispatch_chunk,
            )
        return AsyncRateLimiter(async_storage)
    if args.storage == "sharded":
        from ..tpu.batcher import AsyncTpuStorage  # noqa: lazy per-branch
        from ..tpu.sharded import TpuShardedStorage

        cli_global_ns = {
            ns for ns in (args.global_namespaces or "").split(",") if ns
        }
        mesh = _pod_local_mesh()
        storage = _try_restore(
            args.snapshot_path,
            lambda p: TpuShardedStorage.restore(
                p, mesh=mesh, cache_size=args.cache_size
            ),
            "sharded counter table",
        )
        if storage is not None:
            overrides = [
                (name, cli, snap)
                for name, cli, snap in (
                    ("--tpu-capacity", args.tpu_capacity,
                     storage._local_capacity),
                    ("--global-region", args.global_region,
                     storage._global_region),
                    ("--global-namespaces", cli_global_ns,
                     storage._global_ns),
                )
                if cli != snap
            ]
            for name, cli, snap in overrides:
                log.warning(
                    f"warning: snapshot {name}={snap!r} overrides the "
                    f"command line's {cli!r} (key routing must match "
                    "the checkpoint)")
        if storage is None:
            storage = TpuShardedStorage(
                mesh=mesh,
                local_capacity=args.tpu_capacity,
                cache_size=args.cache_size,
                global_namespaces=sorted(cli_global_ns),
                global_region=args.global_region,
            )
            # Slice-mapped restore (ISSUE 15): the exact checkpoint for
            # this host's CURRENT shard range is missing (first boot,
            # or the membership changed since the last checkpoint) —
            # re-key every sibling checkpoint and seed only the
            # counters this host owns now.
            if getattr(args, "_pod_snapshot_base", None):
                _seed_from_sibling_snapshots(
                    storage,
                    args._pod_snapshot_base,
                    args._pod_owned_shards,
                    args._pod_total_shards,
                )
        if getattr(args, "_pod_snapshot_meta", None):
            storage.snapshot_meta = args._pod_snapshot_meta
        async_storage = AsyncTpuStorage(
            storage, max_delay=args.batch_delay_us / 1e6,
            dispatch_chunk=args.dispatch_chunk,
        )
        if args.pipeline in ("compiled", "native"):
            if args.pipeline == "native":
                log.warning(
                    "native pipeline is single-chip only; using the "
                    "compiled pipeline with sharded storage")
            from ..tpu.pipeline import CompiledTpuLimiter  # noqa: lazy per-branch

            return CompiledTpuLimiter(
                async_storage,
                plan_cache_size=getattr(args, "plan_cache_size", 1 << 16),
                dispatch_chunk=args.dispatch_chunk,
            )
        return AsyncRateLimiter(async_storage)
    if args.storage == "disk":
        try:
            from ..storage.disk import DiskStorage
        except ImportError as exc:
            raise SystemExit(f"storage 'disk' unavailable: {exc}") from None

        path = args.disk_path or "limitador_counters.db"
        return RateLimiter(DiskStorage(path))
    if args.storage == "cached":
        from ..storage.cached import CachedCounterStorage

        if args.authority_url:
            from ..storage.authority import RemoteAuthority

            authority = RemoteAuthority(
                args.authority_url, timeout=args.response_timeout / 1000.0
            )
        else:
            from ..storage.disk import DiskStorage  # noqa: lazy per-branch

            authority = DiskStorage(args.disk_path or "limitador_counters.db")
        return AsyncRateLimiter(
            CachedCounterStorage(
                authority,
                flush_period=args.flush_period / 1000.0,
                batch_size=args.batch_size,
                max_cached=args.max_cached,
                on_partitioned=on_partitioned,
            )
        )
    if args.storage == "distributed":
        try:
            from ..storage.distributed import CrInMemoryStorage
        except ImportError as exc:
            raise SystemExit(
                f"storage 'distributed' unavailable: {exc}"
            ) from None

        return RateLimiter(
            CrInMemoryStorage(
                node_id=args.node_id or "node",
                listen_address=args.listen_address or "0.0.0.0:5001",
                advertise_address=args.advertise_address,
                peers=args.peer or [],
            )
        )
    raise SystemExit(f"unknown storage {args.storage!r}")


async def _amain(args) -> int:
    from ..observability import tracing as tracing_mod
    from ..observability.tracing import configure_tracing

    tracing_err = configure_tracing(args.tracing_endpoint)
    if tracing_err:
        log.warning(tracing_err)
    tracing_mod.set_sample_rate(args.tracing_sample_rate)
    if args.tracing_sample_rate < 1.0:
        log.info(
            f"tracing head sampling: {tracing_mod.sample_rate():.4f} "
            "(datastore_latency aggregation unsampled)")

    # Arm/disarm the serving-model fit BEFORE any storage construction:
    # DeviceStatsRecorder attaches its ingest tap at creation time
    # (set_metrics), so the flag must win over the ambient env first.
    from ..observability import model as model_mod

    model_mod.set_model_fit_enabled(args.model_fit == "on")

    # Persistent XLA compilation cache (ISSUE 18): armed BEFORE pod
    # formation / any jit so every compile this process does lands in
    # (or is served from) the on-disk cache — the cross-restart half of
    # the warm-standby story, and a straight warm-up win for ANY
    # restarting host.
    if args.xla_cache_dir:
        import jax

        os.makedirs(args.xla_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", args.xla_cache_dir)
        # cache everything: the default heuristics skip "fast" compiles,
        # which is exactly the pow2 bucket fleet a standby re-pays
        try:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0
            )
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:
            pass  # knob names vary across jax versions; dir alone works
        log.info(f"persistent XLA compilation cache: {args.xla_cache_dir}")

    # Pod formation MUST precede any storage/jax work: after
    # jax.distributed.initialize the device list is pod-global and the
    # sharded branch picks the host-local mesh off it. Snapshot and
    # failover state stay strictly per-host (each host checkpoints its
    # own shard block; a restarted host restores only its own).
    pod = None
    if args.pod_processes > 1 or args.pod_coordinator:
        # The platform pin must land BEFORE pod formation, not just in
        # build_limiter: initialize_pod's device discovery otherwise
        # probes every backend plugin first, and on an accelerator-less
        # box the TPU plugin's metadata retries can stall a pod host's
        # boot for minutes (whichever process loses the libtpu lockfile
        # race pays the slow probe).
        _pin_platform()
        if args.pod_processes > 1 and not args.pod_coordinator:
            raise SystemExit(
                "--pod-processes > 1 requires --pod-coordinator "
                "(env TPU_POD_COORDINATOR)"
            )
        if not (0 <= args.pod_process_id < args.pod_processes):
            raise SystemExit(
                f"--pod-process-id {args.pod_process_id} outside "
                f"[0, {args.pod_processes})"
            )
        from ..parallel import initialize_pod

        pod = initialize_pod(
            args.pod_coordinator, args.pod_processes, args.pod_process_id
        )
        log.info(
            f"pod formed: process {pod.process_id}/{pod.num_processes}, "
            f"{pod.local_device_count} local of "
            f"{pod.global_device_count} global devices")
        if args.snapshot_path:
            # Snapshot names are keyed by OWNED SHARD RANGE, not host
            # id (ISSUE 15): after a membership change the exact file
            # for the new range is missing and the sharded branch
            # re-keys every sibling checkpoint (including legacy
            # .host<id> names) through the slice-granular decode,
            # seeding only the counters this host owns under the NEW
            # topology — instead of silently loading the wrong host's
            # table (or refusing).
            sph = max(pod.local_device_count, 1)
            lo = pod.process_id * sph
            args._pod_snapshot_base = args.snapshot_path
            args._pod_owned_shards = (lo, lo + sph)
            args._pod_total_shards = pod.num_processes * sph
            args._pod_snapshot_meta = {
                "owned_shards": [lo, lo + sph],
                "topology": {
                    "hosts": pod.num_processes,
                    "host_id": pod.process_id,
                    "shards_per_host": sph,
                    "total_shards": pod.num_processes * sph,
                },
            }
            args.snapshot_path = (
                f"{args.snapshot_path}.shards{lo}-{lo + sph}"
            )
            log.info(
                f"pod: per-shard-range snapshot path "
                f"{args.snapshot_path}")

    initial_labels = args.metric_labels
    if args.metric_labels_file:
        try:
            with open(args.metric_labels_file) as f:
                content = f.read().strip()
            if content:
                initial_labels = content
        except OSError as exc:
            log.warning(
                f"metric labels file unreadable ({exc}); "
                "using --metric-labels")
    metrics = PrometheusMetrics(
        use_limit_name_label=args.limit_name_in_labels,
        metric_labels=initial_labels,
    )
    if args.metrics_exemplars == "on":
        metrics.enable_exemplars()
        log.info(
            "metrics exemplars on: /metrics renders the OpenMetrics "
            "exposition with trace-id exemplars on tail latency buckets")
    # Span-tree latency aggregation — the same two aggregates the
    # reference's subscriber registers (main.rs:908-917): request-path
    # datastore spans roll up under should_rate_limit, write-behind
    # authority I/O under flush_batcher_and_update_counters.
    from ..observability.metrics_layer import MetricsLayer, install

    install(
        MetricsLayer()
        .gather(
            "should_rate_limit",
            metrics.record_datastore_latency,
            ["datastore"],
        )
        .gather(
            "flush_batcher_and_update_counters",
            metrics.record_datastore_latency,
            ["datastore"],
        )
    )
    labels_watcher = None
    if args.metric_labels_file:

        def _load_labels(path):
            with open(path) as f:
                return f.read().strip()

        def _labels_changed(content):
            try:
                if content:
                    metrics.reload_labels(content)
                    log.info("metric labels reloaded")
            except Exception as exc:  # bad CEL must not kill the watcher
                log.warning(f"metric labels reload rejected: {exc}")

        labels_watcher = LimitsFileWatcher(
            args.metric_labels_file,
            _labels_changed,
            on_error=lambda exc: log.warning(
                f"metric labels file reload failed: {exc}"),
            loader=_load_labels,
            poll_interval=args.limits_poll_interval,
        )
        labels_watcher.start()
    limiter = build_limiter(
        args,
        on_partitioned=(
            lambda v: metrics.datastore_partitioned.set(1 if v else 0)
        ),
    )
    # Shard-aware routed frontend: wrap the limiter so every decision is
    # either locally owned (the collective-free lean path) or forwarded
    # ONCE over the peer lane to its owner host. Wrapping happens before
    # any consumer captures the limiter, so the RLS/HTTP planes, the
    # serving shards and the metrics wiring all see the routed surface.
    pod_frontend = None
    if pod is not None and pod.num_processes > 1:
        from ..routing import PodRouter, PodTopology
        from .peering import PeerLane, PodFrontend, PodResilience

        peer_urls = args.pod_peer or [
            u for u in (_env("TPU_POD_PEERS") or "").split(",") if u
        ]
        if len(peer_urls) != pod.num_processes:
            raise SystemExit(
                f"pod: need one --pod-peer per process "
                f"({pod.num_processes}), got {len(peer_urls)}"
            )
        # --pod-degraded-mode off pins the PR 10 posture exactly: no
        # retry, no breaker/failover — a peer failure fails that
        # request. Hedging stays its own opt-in (--pod-hedge-ms).
        degraded = args.pod_degraded_mode == "on"
        resilience = PodResilience(
            degraded=degraded,
            retry=degraded,
            hedge_ms=max(args.pod_hedge_ms, 0.0),
            breaker_failures=args.pod_peer_breaker_failures,
            breaker_reset_s=args.pod_peer_breaker_reset_ms / 1e3,
            probe_interval_s=float(_env("TPU_POD_PROBE_MS", "500")) / 1e3,
        )
        lane = PeerLane(
            pod.process_id,
            args.pod_peer_listen or f"{args.rls_host}:{args.rls_port + 2}",
            {
                i: url
                for i, url in enumerate(peer_urls)
                if i != pod.process_id
            },
            None,
            resilience=resilience,
        )
        # NOT started here: the lane begins serving only after the
        # initial limits load below — a restarting host must never
        # answer a forwarded decision against an empty limits set
        # (it would silently admit traffic its peers expect limited).
        router = PodRouter(PodTopology(
            hosts=pod.num_processes,
            host_id=pod.process_id,
            shards_per_host=max(pod.local_device_count, 1),
        ))
        pod_global_ns = {
            ns for ns in (args.global_namespaces or "").split(",") if ns
        }
        pod_frontend = PodFrontend(
            limiter, router, lane, global_namespaces=pod_global_ns,
            resilience=resilience,
            events_capacity=max(args.pod_events, 1),
        )
        limiter = pod_frontend
        log.info(
            f"pod routed ingress: host {pod.process_id} owns global "
            f"shards "
            f"[{pod.process_id * router.topology.shards_per_host}, "
            f"{(pod.process_id + 1) * router.topology.shards_per_host})")
        log.info(
            "pod resilience: degraded-owner failover "
            f"{'on' if degraded else 'off'}, hedge "
            f"{resilience.hedge_ms:.0f}ms, breaker "
            f"{resilience.breaker_failures} failures / "
            f"{resilience.breaker_reset_s * 1e3:.0f}ms reset")
        if args.pod_resize == "on":
            # Elastic pod (ISSUE 15): arm the live-resize plane.
            # Everything stays inert until POST /debug/pod/resize (or a
            # peer's resize proposal) drives a transition — except that
            # forwards now stamp the topology epoch and the wrong-owner
            # gate serves, which is the point of arming.
            from .resize import PodResizeCoordinator

            coordinator = PodResizeCoordinator(
                pod_frontend,
                peers={i: url for i, url in enumerate(peer_urls)},
                listen_address=peer_urls[pod.process_id],
                slice_pause_s=float(
                    _env("TPU_POD_RESIZE_SLICE_PAUSE_MS", "0") or 0
                ) / 1e3,
                transition_timeout_s=float(
                    _env("TPU_POD_RESIZE_TIMEOUT_S", "60") or 60
                ),
            )
            pod_frontend.attach_resize(coordinator)
            log.info(
                "elastic pod armed: POST /debug/pod/resize drives live "
                "resize/add_host/drain_host (topology epoch "
                f"{pod_frontend.router.topology_epoch})")
        if args.pod_psum_lane == "on" and pod_global_ns:
            # Lockstep psum lane (ISSUE 13): eligible fixed-window
            # global namespaces decide locally on EVERY host against
            # lockstep-exchanged remote partials instead of funneling
            # through one pin host. Attached before the initial limits
            # load so configure_with claims namespaces on first apply;
            # the pacer starts only then (all hosts reach the first
            # barrier with limits loaded).
            from ..parallel.mesh import PodPsumLane

            psum_lane = PodPsumLane(pod.num_processes, pod.process_id)
            pod_frontend.attach_psum_lane(psum_lane)
            psum_lane.start(
                interval_s=max(args.pod_psum_interval_ms, 10.0) / 1e3
            )
            log.info(
                "pod psum lane: lockstep exchange every "
                f"{max(args.pod_psum_interval_ms, 10.0):.0f}ms "
                f"(global namespaces: {sorted(pod_global_ns)})")
    if args.standby == "on":
        if pod_frontend is not None:
            log.warning(
                "--standby on ignored: this process already formed a "
                "pod (a member is not a standby)")
        else:
            # Warm standby (ISSUE 18): a single-host boot that forms
            # its host-local mesh, pre-compiles the pow2 hit-bucket
            # kernels and serves the peer lane memberless — hosts=1 /
            # host_id=0 is provisional, overwritten when a running
            # pod's join_host ships the real topology over the
            # join_admin lane kind.
            from ..routing import PodRouter, PodTopology  # noqa: lazy per-branch
            from .peering import PeerLane, PodFrontend, PodResilience  # noqa: lazy per-branch
            from .resize import PodResizeCoordinator  # noqa: lazy per-branch
            from .standby import WarmStandby

            degraded = args.pod_degraded_mode == "on"
            resilience = PodResilience(
                degraded=degraded,
                retry=degraded,
                hedge_ms=max(args.pod_hedge_ms, 0.0),
                breaker_failures=args.pod_peer_breaker_failures,
                breaker_reset_s=args.pod_peer_breaker_reset_ms / 1e3,
                probe_interval_s=float(
                    _env("TPU_POD_PROBE_MS", "500")
                ) / 1e3,
            )
            standby_listen = (
                args.pod_peer_listen
                or f"{args.rls_host}:{args.rls_port + 2}"
            )
            lane = PeerLane(
                0, standby_listen, {}, None, resilience=resilience,
            )
            router = PodRouter(PodTopology(
                hosts=1, host_id=0, shards_per_host=1,
            ))
            pod_frontend = PodFrontend(
                limiter, router, lane,
                global_namespaces={
                    ns for ns in
                    (args.global_namespaces or "").split(",") if ns
                },
                resilience=resilience,
                events_capacity=max(args.pod_events, 1),
            )
            limiter = pod_frontend
            coordinator = PodResizeCoordinator(
                pod_frontend,
                peers={},
                listen_address=standby_listen,
                transition_timeout_s=float(
                    _env("TPU_POD_RESIZE_TIMEOUT_S", "60") or 60
                ),
            )
            pod_frontend.attach_resize(coordinator)
            standby = WarmStandby(
                pod_frontend, coordinator,
                table_capacity=(
                    args.tpu_capacity
                    if args.storage in ("tpu", "sharded") else None
                ),
            )
            standby.warm()
            log.info(
                f"warm standby: peer lane at {standby_listen}, "
                "waiting for a coordinator's join "
                "(POST /debug/pod/join on any pod member)")
    counters_storage = limiter.storage.counters
    # Prefer the limiter (the compiled pipeline aggregates its storage's
    # stats and adds compiler eval counters); otherwise the storage itself.
    stats_source = (
        limiter if hasattr(limiter, "library_stats") else counters_storage
    )
    if hasattr(stats_source, "library_stats"):
        metrics.attach_library_source(stats_source)
    for target in (limiter, counters_storage):
        if hasattr(target, "set_metrics"):
            target.set_metrics(metrics)
            break
    # Native telemetry plane + SLO burn-rate watchdog (observability/
    # native_plane.py): arms the C-side histograms/exemplars, merges
    # them into /metrics on every render, feeds the watchdog from the
    # device-plane recorder and serves the /debug/stats sections.
    # Device storages only — host-only backends have no native lane to
    # measure (and should not pay a native build for a watchdog).
    native_plane = None
    if args.storage == "tpu":
        from ..observability.native_plane import NativePlane

        native_plane = NativePlane(
            budget_ms=args.slo_budget_ms,
            slow_row_us=args.native_slow_row_us,
            trace_sample=args.native_trace_sample,
        )
        # The recorder lives on whichever target set_metrics landed on:
        # the compiled limiter carries its own; the standard pipeline's
        # AsyncRateLimiter does not, so the storage's recorder is the
        # process flight recorder + SLO feed there.
        recorder = (
            getattr(limiter, "recorder", None)
            or getattr(counters_storage, "recorder", None)
        )
        if recorder is not None:
            native_plane.attach_recorder(recorder)
        metrics.attach_native_plane(native_plane)
    # Admission plane: overload control, priority shedding, device-plane
    # breaker + host failover (admission/). Only the batched TPU
    # storages expose set_admission — the host backends have no device
    # plane to fail over from.
    admission = None
    if args.admission_mode != "off":
        if not hasattr(counters_storage, "set_admission"):
            log.warning(
                f"--admission-mode {args.admission_mode} requires a "
                f"batched tpu storage (got {args.storage!r}); admission "
                "plane disabled")
        else:
            from ..admission import (
                AdaptiveLimiter,
                AdmissionController,
                CircuitBreaker,
                PriorityResolver,
            )

            admission = AdmissionController(
                mode=args.admission_mode,
                metrics=metrics,
                breaker=CircuitBreaker(
                    failure_threshold=args.breaker_failures,
                    stall_timeout=args.breaker_stall_ms / 1000.0,
                    reset_timeout=args.breaker_reset_ms / 1000.0,
                ),
                overload=AdaptiveLimiter(
                    max_inflight=args.max_inflight,
                    target_queue_wait=(
                        args.admission_target_queue_ms / 1000.0
                    ),
                ),
                priorities=PriorityResolver(
                    descriptor_key=args.priority_key,
                    namespace_map=PriorityResolver.parse_namespace_map(
                        args.priority or ()
                    ),
                ),
                shed_response=args.shed_response,
            )
            counters_storage.set_admission(admission)
            if hasattr(limiter, "fail_over_queued"):
                admission.add_drainable(limiter)
            admission.start(asyncio.get_running_loop())
            log.info(
                f"admission plane: mode={args.admission_mode}, "
                f"max-inflight={args.max_inflight}, breaker "
                f"stall={args.breaker_stall_ms:.0f}ms/"
                f"reset={args.breaker_reset_ms:.0f}ms, "
                f"shed-response={args.shed_response}")
    # gRPC server reflection is always on, from the vendored SDK-free
    # implementation (server/reflection.py) — the reference serves it
    # unconditionally too (envoy_rls/server.rs:232-263). The historical
    # --grpc-reflection-service flag is accepted and now a no-op.
    if args.grpc_reflection_service:
        log.info("grpc reflection is always enabled (vendored); "
                 "--grpc-reflection-service is a no-op")
    status = {"limits_file_version": 0, "limits_file_errors": 0}
    pipelines_to_invalidate = []

    async def apply_limits(limits):
        # AsyncRateLimiter and the pod frontend configure async; the
        # host-only backends are plain sync.
        applied = limiter.configure_with(limits)
        if inspect.isawaitable(applied):
            await applied
        for pipeline in pipelines_to_invalidate:
            pipeline.invalidate()
        if admission is not None:
            # Re-derive namespace priorities from `priority:` annotations.
            admission.priorities.refresh(limits)

    watcher = None
    if args.limits_file:
        loop = asyncio.get_running_loop()

        def on_change(limits):
            status["limits_file_version"] += 1
            fut = asyncio.run_coroutine_threadsafe(apply_limits(limits), loop)

            def _applied(f):
                exc = f.exception()
                if exc is not None:
                    # e.g. an edit adding a policy this storage rejects:
                    # keep serving the previous config, count the error.
                    status["limits_file_errors"] += 1
                    log.warning(f"limits reload rejected: {exc}")

            fut.add_done_callback(_applied)

        def on_error(exc):
            status["limits_file_errors"] += 1
            log.warning(f"limits file reload failed: {exc}")

        # Construct the watcher (capturing its baseline stamp) BEFORE the
        # initial load, so a file replaced between load and watch (e.g. a
        # ConfigMap symlink flip during startup) still triggers a reload.
        watcher = LimitsFileWatcher(
            args.limits_file, on_change, on_error,
            poll_interval=args.limits_poll_interval,
        )
        limits = load_limits_file(args.limits_file)
        try:
            await apply_limits(limits)
        except ValueError as exc:
            # e.g. a token_bucket limit on a storage whose cell format
            # can't count it — a config error, not a crash.
            raise SystemExit(f"limits file rejected: {exc}") from None
        status["limits_file_version"] = 1
        watcher.start()

    if pod_frontend is not None:
        # Limits are loaded (and the router configured) — the peer
        # lane may now answer forwarded decisions. Until this point
        # peers' forwards to this host fail fast (connection refused,
        # counted in their pod_peer_errors) instead of silently
        # admitting against an empty limits set.
        pod_frontend.lane.start()
        log.info(
            f"pod peer lane serving on "
            f"{pod_frontend.lane.listen_address} "
            f"(port {pod_frontend.lane.port})")

    native_pipeline = None
    if (
        pod_frontend is not None
        and args.storage == "tpu"
        and args.pipeline == "native"
        and not _pod_native_capable(args, log)
    ):
        # Capability check (ISSUE 13): the shard-aware hot lane is the
        # only native plane that routes foreign-owned keys, so pod mode
        # refuses the pipeline ONLY when that lane cannot serve (C
        # library absent/stale, or --native-hot-lane off) — the same
        # warn-and-fallback shape as --native-hot-lane itself.
        pass
    elif args.storage == "tpu" and args.pipeline == "native":
        from .. import native as native_mod

        if native_mod.available():
            from ..tpu.native_pipeline import NativeRlsPipeline

            native_pipeline = NativeRlsPipeline(
                limiter, metrics, max_delay=args.batch_delay_us / 1e6,
                plan_cache_size=args.plan_cache_size,
                dispatch_chunk=args.dispatch_chunk,
                hot_lane=args.native_hot_lane == "on",
            )
            if (
                args.native_hot_lane == "on"
                and not native_pipeline.hot_lane_active
            ):
                log.warning(
                    "native hot lane requested but unavailable (library "
                    "without lane symbols, or plan cache disabled); "
                    "serving through the pure-Python cached lane")
            if pod_frontend is not None:
                if native_pipeline.hot_lane_active:
                    # Pod fast path (ISSUE 13): the C mirror learns the
                    # topology, plans stamp their owner host, and the
                    # lane's bulk_decide handler decides forwarded blob
                    # batches — the zero-Python plane now serves pod
                    # mode. The pipeline's exact fallback is the pod
                    # frontend itself (limiter == pod_frontend here),
                    # so slow rows keep full routed semantics.
                    try:
                        pod_frontend.attach_pipeline(native_pipeline)
                    except RuntimeError as exc:
                        # e.g. a pod bigger than the int8 owner
                        # encoding — mis-routing is never an option.
                        log.warning(
                            f"pod mode: cannot arm the hot lane "
                            f"({exc}); serving through the routed "
                            "compiled pipeline")
                        native_pipeline = await _discard_pipeline(
                            native_pipeline)
                    else:
                        log.info(
                            "pod fast path: shard-aware native hot "
                            "lane on (foreign-owned rows bulk-forward "
                            "per flush)")
                else:
                    # Without the plan mirror the pipeline would decide
                    # against local storage only, bypassing the router.
                    log.warning(
                        "pod mode: the hot lane did not come up; "
                        "serving through the routed compiled pipeline")
                    native_pipeline = await _discard_pipeline(
                        native_pipeline)
            if native_pipeline is not None:
                pipelines_to_invalidate.append(native_pipeline)
                metrics.attach_library_source(native_pipeline)
            if admission is not None and native_pipeline is not None:
                admission.add_drainable(native_pipeline)
            if args.lease_mode == "on" and native_pipeline is not None:
                if native_pipeline.hot_lane_active:
                    from ..lease import LeaseConfig

                    try:
                        native_pipeline.attach_lease(LeaseConfig(
                            max_tokens=args.lease_max_tokens,
                        ))
                        log.info(
                            "limitador-tpu: quota-lease tier on "
                            f"(max {args.lease_max_tokens} tokens/lease)")
                    except RuntimeError as exc:
                        # e.g. a storage without the credit lane
                        # (sharded/global counters stay exact by design)
                        log.warning(
                            f"--lease-mode on unavailable: {exc}; "
                            "serving without the lease tier")
                else:
                    log.warning(
                        "--lease-mode on requires the native hot lane "
                        "(plan mirror); serving without the lease tier")
        else:
            log.warning(
                f"native hostpath unavailable "
                f"({native_mod.build_error()}); using compiled pipeline")

    if args.lease_mode == "on" and native_pipeline is None:
        log.warning(
            "--lease-mode on requires tpu storage with --pipeline native; "
            "serving without the lease tier")

    # Tenant usage observatory + unified control-signal bus (ISSUE 8):
    # periodic heavy-hitter drains with slot->counter attribution
    # (GET /debug/top, tenant_* families) and the joined ControlSignals
    # observation vector (GET /debug/signals, signal_* families) —
    # device-backed storages only (the accumulator lives in the device
    # table).
    observatory = None
    signal_bus = None
    device_storage = getattr(counters_storage, "inner", counters_storage)
    if args.usage_topk > 0 and hasattr(device_storage, "drain_hot_slots"):
        from ..observability.signals import SignalBus
        from ..observability.usage import TenantUsageObservatory

        signal_bus = SignalBus()
        signal_bus.warm()  # calibration probe off-thread
        observatory = TenantUsageObservatory(
            device_storage,
            pipeline=native_pipeline,
            top_k=args.usage_topk,
            interval_s=args.usage_drain_interval,
            near_threshold=args.usage_near_threshold,
            signal_bus=signal_bus,
        )
        bus_recorder = (
            getattr(limiter, "recorder", None)
            or getattr(counters_storage, "recorder", None)
        )
        if bus_recorder is not None:
            signal_bus.attach_recorder(bus_recorder)
        if admission is not None:
            signal_bus.attach_admission(admission)
        if native_pipeline is not None:
            signal_bus.attach_pipeline(native_pipeline)
        if native_plane is not None:
            signal_bus.attach_native_plane(native_plane)
        signal_bus.attach_observatory(observatory)
        metrics.attach_render_hook(observatory)
        metrics.attach_render_hook(signal_bus)
        observatory.start()
        log.info(
            f"tenant usage observatory: top-{args.usage_topk} drained "
            f"every {args.usage_drain_interval:.1f}s"
            + (", native leased merge on"
               if native_pipeline is not None else ""))

    # Pod observability plane (ISSUE 12): hop breakdown into the
    # process flight recorder + the pod_hop_phase_ms family, the local
    # ControlSignals bus federated over the lane, and the event
    # counters polled off library_stats (wired by PodFrontend itself).
    if pod_frontend is not None:
        pod_recorder = (
            getattr(limiter, "recorder", None)
            or getattr(counters_storage, "recorder", None)
        )
        if pod_recorder is not None:
            pod_frontend.attach_flight(pod_recorder)
        if signal_bus is not None:
            pod_frontend.attach_signal_bus(signal_bus)
        metrics.attach_render_hook(pod_frontend.hops)
        log.info(
            "pod observability plane: hop tracing, "
            f"{args.pod_events}-event timeline, federated signals "
            f"{'with' if signal_bus is not None else 'without'} the "
            "local signal bus")

    # Serving-model observatory (ISSUE 14): the online coefficient fit
    # over the recorder's per-launch observations, refit on the usage
    # observatory's drain thread, served at GET /debug/capacity and
    # joined into the ControlSignals tail. Device storages only — the
    # fit's observation unit is a device launch.
    model_estimator = None
    model_recorder = (
        getattr(limiter, "recorder", None)
        or getattr(counters_storage, "recorder", None)
    )
    if args.model_fit == "on" and model_recorder is not None:
        model_estimator = model_mod.process_estimator()
        model_estimator.budget_ms = args.slo_budget_ms
        # set_metrics predates the flag resolution in subprocess-spawn
        # orders; make the attachment explicit either way
        model_recorder.model = model_estimator
        model_estimator.attach_context(model_mod.pipeline_context(
            pipeline=native_pipeline, pod=pod_frontend,
            # sharded_launches lives on the STORAGE's library_stats
            # (merged by the batcher over the sharded pipeline) —
            # the native pipeline's stats never carry it
            storage=(
                counters_storage
                if hasattr(counters_storage, "library_stats") else None
            ),
        ))
        if pod_frontend is not None:
            events_log = getattr(pod_frontend, "events", None)
            if events_log is not None:
                model_estimator.attach_event_log(events_log)
        if signal_bus is not None:
            signal_bus.attach_model(model_estimator)
        if observatory is not None:
            observatory.model = model_estimator
        metrics.attach_render_hook(model_estimator)
        log.info(
            "serving-model observatory: online fit armed "
            f"(SLO budget {args.slo_budget_ms:.1f}ms, refit on the "
            "usage drain cadence; GET /debug/capacity)")

    # Flight recorder (ISSUE 16): always-on sampled exemplar rings +
    # worst-K tails on every decision lane, a trigger engine turning
    # SLO-burn/breaker/resize/drift/probe edges (and manual POST
    # /debug/flight/trigger) into self-contained incident bundles, and
    # pod-correlated peer ring collection over the peer lane.
    flight_engine = None
    if args.flight == "on":
        from ..observability.device_plane import (
            JaxProfiler as _FlightProfiler,
        )
        from ..observability.flight import (
            BundleSpool,
            FlightRecorder,
            TriggerEngine,
        )

        flight = FlightRecorder(
            sample_stride=max(args.flight_sample, 1),
            host_id=pod.process_id if pod is not None else 0,
        )
        flight.trace_provider = tracing_mod.current_trace_id
        flight_rec_target = (
            getattr(limiter, "recorder", None)
            or getattr(counters_storage, "recorder", None)
        )
        if flight_rec_target is not None:
            # The lean-lane tap: every batched decision the device
            # recorder times now offers the sampled stage breakdown.
            flight_rec_target.flight_tap = flight
        if pod_frontend is not None:
            pod_frontend.attach_flight_recorder(flight)
        flight_engine = TriggerEngine(
            flight,
            BundleSpool(args.flight_spool_dir),
            signals=signal_bus,
            events=(
                getattr(pod_frontend, "events", None)
                if pod_frontend is not None else None
            ),
            lane=pod_frontend.lane if pod_frontend is not None else None,
            profiler=(
                _FlightProfiler(args.profile_dir)
                if args.flight_profile_s > 0 else None
            ),
            window_s=args.flight_window,
            profile_s=args.flight_profile_s,
        )
        flight_engine.start()
        metrics.attach_render_hook(flight)
        log.info(
            "flight recorder armed: 1-in-"
            f"{max(args.flight_sample, 1)} exemplars + worst-K tails, "
            f"{args.flight_window:.0f}s bundle window, spool "
            f"{args.flight_spool_dir} (GET /debug/flight)")

    # Tiered storage (ISSUE 17): arm the migration thread over the
    # TieredStorage facade constructed in _build_limiter. Wired late so
    # it can see the lease broker (demotions settle outstanding tokens
    # first), the serving-model estimator (migration pricing), the pod
    # event log (tier_migration timeline) and the flight recorder (the
    # cold_tier decision lane).
    tier_manager = None
    if getattr(args, "tier_mode", "off") == "on":
        from ..tier import TieredStorage, TierManager

        tier_storage = getattr(counters_storage, "inner", counters_storage)
        if not isinstance(tier_storage, TieredStorage):
            log.warning(
                "--tier-mode on requires plain tpu storage (no "
                "peer/sharded mode); serving single-tier")
        else:
            tier_manager = TierManager(
                tier_storage,
                broker=(
                    native_pipeline.lease_broker
                    if native_pipeline is not None else None
                ),
                estimator=model_estimator,
                events=(
                    getattr(pod_frontend, "events", None)
                    if pod_frontend is not None else None
                ),
                observatory=observatory,
                interval_s=args.tier_migrate_interval,
            )
            if args.flight == "on":
                tier_storage.flight_tap = flight
            tier_manager.start()
            metrics.attach_render_hook(tier_manager)
            log.info(
                "tiered storage: device hot set over exact host cold "
                f"tier, migration every {args.tier_migrate_interval:.1f}s"
                + (
                    f", cold spill -> {args.tier_cold}"
                    if args.tier_cold else ""
                )
                + " (GET /debug/tiering)")

    # Capacity controller (ISSUE 20): one model-based loop jointly
    # actuating admission ceiling, shed floor, chunk target, lease
    # scale and pod membership. Wired last so the actuator binds every
    # live subsystem; off (the default) constructs nothing.
    capacity_controller = None
    if args.capacity_controller != "off":
        from ..control import (
            CapacityController,
            ModelPolicy,
            ServerActuator,
        )

        ctl_planners = []
        if hasattr(counters_storage, "_batcher_pairs"):
            for mb, _ub in counters_storage._batcher_pairs():
                cp = getattr(mb, "chunk_planner", None)
                if cp is not None:
                    ctl_planners.append(cp)
        cp = getattr(native_pipeline, "chunk_planner", None)
        if cp is not None:
            ctl_planners.append(cp)
        ctl_coordinator = (
            getattr(pod_frontend, "resize", None)
            if pod_frontend is not None else None
        )
        ctl_actuator = ServerActuator(
            overload=admission.overload if admission is not None else None,
            admission=admission,
            planners=ctl_planners,
            broker=(
                native_pipeline.lease_broker
                if native_pipeline is not None else None
            ),
            coordinator=ctl_coordinator,
            standby_addresses=[
                a.strip() for a in args.ctl_standby.split(",")
                if a.strip()
            ],
            min_hosts=args.ctl_min_hosts,
            max_hosts=args.ctl_max_hosts,
        )
        capacity_controller = CapacityController(
            ctl_actuator,
            policy=ModelPolicy(
                budget_ms=args.slo_budget_ms,
                grow_headroom=args.ctl_grow_headroom,
                shrink_headroom=args.ctl_shrink_headroom,
            ),
            signals=signal_bus,
            estimator=model_estimator,
            events=(
                getattr(pod_frontend, "events", None)
                if pod_frontend is not None else None
            ),
            mode=args.capacity_controller,
            interval_s=args.ctl_interval,
            sustain_s=args.ctl_sustain,
            dwell_s=args.ctl_dwell,
        )
        if signal_bus is not None:
            signal_bus.attach_controller(capacity_controller)
        metrics.attach_render_hook(capacity_controller)
        capacity_controller.start()
        log.info(
            "capacity controller "
            f"{'ON' if args.capacity_controller == 'on' else 'observing'}: "
            f"{len(ctl_actuator.specs())} knobs, membership "
            f"{'armed' if ctl_coordinator is not None else 'unavailable'}, "
            f"tick {args.ctl_interval:.1f}s, sustain "
            f"{args.ctl_sustain:.0f}s, dwell {args.ctl_dwell:.0f}s")

    authority_server = None
    if args.authority_listen:
        from ..storage.authority import serve_authority

        sync_storage = limiter.storage.counters
        inner = getattr(sync_storage, "inner", None)
        if inner is not None:
            sync_storage = inner  # AsyncTpuStorage -> the device table
        if not hasattr(sync_storage, "apply_deltas"):
            raise SystemExit(
                f"--authority-listen: storage {args.storage!r} cannot act "
                "as a shared authority (no apply_deltas)"
            )
        authority_server = serve_authority(sync_storage, args.authority_listen)
        log.info(
            f"limitador-tpu: shared authority on {args.authority_listen} "
            f"(port {authority_server.port})")

    native_ingress = None
    rls_grpc_port = args.rls_port
    if args.native_ingress:
        from ..native.ingress import (
            NativeIngress,
            ingress_available,
            ingress_build_error,
        )

        if native_pipeline is None:
            log.warning(
                "--native-ingress requires tpu storage with --pipeline "
                "native (and the native library); serving Python gRPC only")
        elif args.rate_limit_headers != "NONE":
            log.warning(
                "--native-ingress does not build response headers; use "
                "--rate-limit-headers NONE (serving Python gRPC only)")
        elif not ingress_available():
            log.warning(
                f"native ingress unavailable ({ingress_build_error()}); "
                "serving Python gRPC only")
        else:
            # Cold-path methods (Kuadrant check/report) route through the
            # same RlsService the Python gRPC server uses, so one port
            # serves the whole surface.
            from .rls import (
                _ENVOY_SERVICE,
                _KUADRANT_SERVICE,
                RlsService,
                make_native_method_handlers,
            )
            from .reflection import (
                REFLECTION_METHOD,
                native_reflection_handler,
            )

            ingress_service = RlsService(
                limiter, metrics, args.rate_limit_headers
            )
            ingress_handlers = make_native_method_handlers(ingress_service)
            ingress_handlers[REFLECTION_METHOD] = native_reflection_handler(
                (_ENVOY_SERVICE, _KUADRANT_SERVICE)
            )
            native_ingress = NativeIngress(
                native_pipeline,
                host=args.rls_host,
                port=args.rls_port,
                loop=asyncio.get_running_loop(),
                handlers=ingress_handlers,
                stream_path=REFLECTION_METHOD,
            )
            rls_grpc_port = args.rls_port + 1
            metrics.attach_library_source(native_ingress)

    rls_server = await serve_rls(
        limiter,
        f"{args.rls_host}:{rls_grpc_port}",
        metrics,
        args.rate_limit_headers,
        native_pipeline=native_pipeline,
        admission=admission,
    )
    # Extra serving shards: thread-per-event-loop gRPC servers on the
    # same port (SO_REUSEPORT). The limiter's per-loop batchers / submit
    # shards fan the accepted traffic into the one shared device lane.
    serving_shards = []
    if args.serving_shards > 1:
        from .rls import RlsServingShard

        for i in range(1, args.serving_shards):
            try:
                serving_shards.append(RlsServingShard(
                    i, limiter, f"{args.rls_host}:{rls_grpc_port}",
                    metrics, args.rate_limit_headers,
                    native_pipeline=native_pipeline, admission=admission,
                ))
            except RuntimeError as exc:
                log.warning(
                    f"serving shard {i} unavailable ({exc}); continuing "
                    f"with {1 + len(serving_shards)} shard(s)")
                break
        if serving_shards:
            log.info(
                f"serving shards: {1 + len(serving_shards)} event loops "
                f"on port {rls_grpc_port}")
    from ..observability.device_plane import JaxProfiler

    debug_sources = [counters_storage]
    if native_pipeline is not None:
        debug_sources.append(native_pipeline)
    if native_plane is not None:
        debug_sources.append(native_plane)
    if observatory is not None:
        debug_sources.append(observatory)
    if signal_bus is not None:
        debug_sources.append(signal_bus)
    if model_estimator is not None:
        debug_sources.append(model_estimator)
    if flight_engine is not None:
        debug_sources.append(flight_engine)
    if tier_manager is not None:
        debug_sources.append(tier_manager)
    if capacity_controller is not None:
        debug_sources.append(capacity_controller)
    http_runner = await run_http_server(
        limiter, args.http_host, args.http_port, metrics, status,
        debug_sources=debug_sources,
        profiler=JaxProfiler(args.profile_dir),
        admission=admission,
    )
    log.info(
        f"limitador-tpu: RLS gRPC on {args.rls_host}:{rls_grpc_port}"
        + (
            f", native HTTP/2 ingress on {args.rls_host}:{native_ingress.port}"
            if native_ingress is not None
            else ""
        )
        + f", HTTP on {args.http_host}:{args.http_port}, "
        f"storage={args.storage}")

    snapshot_task = None
    if args.storage in ("tpu", "sharded") and args.snapshot_path:
        tpu_storage = limiter.storage.counters.inner

        import threading

        snapshot_mutex = threading.Lock()

        def take_snapshot():
            # Serializes periodic vs shutdown snapshots: cancelling the loop
            # task cannot stop an executor thread mid-write, and two writers
            # on one tmp file would publish a corrupt checkpoint.
            with snapshot_mutex:
                tmp = args.snapshot_path + ".tmp"
                tpu_storage.snapshot(tmp)
                os.replace(tmp, args.snapshot_path)

        async def snapshot_loop():
            while True:
                await asyncio.sleep(args.snapshot_period)
                try:
                    await asyncio.get_running_loop().run_in_executor(
                        None, take_snapshot
                    )
                except Exception as exc:
                    # A failed checkpoint (disk full, ...) must not end
                    # periodic checkpointing for the process lifetime.
                    log.warning(f"snapshot failed: {exc}")

        snapshot_task = asyncio.get_running_loop().create_task(snapshot_loop())

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()

    if snapshot_task is not None:
        # Drain any in-flight periodic snapshot before the final one — two
        # writers on the same tmp file would publish a corrupt checkpoint.
        snapshot_task.cancel()
        try:
            await snapshot_task
        except asyncio.CancelledError:
            pass
        try:
            await asyncio.get_running_loop().run_in_executor(
                None, take_snapshot
            )
        except Exception as exc:
            log.warning(f"final snapshot failed: {exc}")

    if watcher:
        watcher.stop()
    if labels_watcher is not None:
        labels_watcher.stop()
    if authority_server is not None:
        authority_server.stop()
    if native_ingress is not None:
        native_ingress.close()
    for shard in serving_shards:
        # Off-loop: shard.stop blocks on the sync server's drain and a
        # thread join; inline it would freeze the aio server's own
        # graceful stop behind a wedged shard.
        await asyncio.get_running_loop().run_in_executor(
            None, shard.stop, 1.0
        )
    await rls_server.stop(grace=1.0)
    await http_runner.cleanup()
    if capacity_controller is not None:
        # First: nothing may actuate (or propose a resize) into
        # subsystems that are shutting down behind it.
        capacity_controller.close()
    if observatory is not None:
        observatory.close()
    if tier_manager is not None:
        # Before the pipeline/storage close: the last round may still
        # settle leases and drain the cold journal to the spill log.
        tier_manager.close()
    if flight_engine is not None:
        flight_engine.stop()
    if admission is not None:
        await admission.close()
    if native_pipeline is not None:
        await native_pipeline.close()
    if pod_frontend is not None:
        pod_frontend.close_pod()
        limiter = pod_frontend._limiter  # close the wrapped limiter
    if hasattr(limiter, "close"):
        # Compiled pipeline: final flush + drain in-flight collects +
        # release worker pools before the storage goes away.
        await limiter.close()
    if isinstance(limiter, AsyncRateLimiter):
        await limiter.storage.counters.close()
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    _setup_logging(args.structured_logs, args.log_level)
    if args.validate:
        if not args.limits_file:
            log.error("--validate requires a limits file")
            return 2
        try:
            limits = load_limits_file(args.limits_file)
        except LimitsFileError as exc:
            log.error(f"INVALID: {exc}")
            return 1
        # Success goes to STDOUT (script-parseable contract, independent
        # of the log format); diagnostics ride the stderr log handler.
        print(f"OK: {len(limits)} limits")
        return 0
    try:
        return asyncio.run(_amain(args))
    except KeyboardInterrupt:
        return 0
    except (ValueError, LimitsFileError, CelError) as exc:
        log.error(f"configuration error: {exc}")
        return 2


if __name__ == "__main__":
    sys.exit(main())
