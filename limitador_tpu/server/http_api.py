"""HTTP admin/check API.

Mirrors /root/reference/limitador-server/src/http_api/server.rs over aiohttp:

    GET  /status            liveness + limits-config version/error counters
    GET  /metrics           Prometheus text exposition
    GET  /limits/{ns}       limits of a namespace (DTO: request_types.rs:19-27)
    GET  /counters/{ns}     live counters with remaining/expires_in_seconds
    POST /check             200/429, read-only (server.rs:127-157)
    POST /report            200, update-only (server.rs:159-183)
    POST /check_and_report  200/429 + optional draft-03 headers
                            (server.rs:185-260)

Beyond the reference surface, the device-plane debug endpoints
(observability/device_plane.py):

    GET  /debug/stats       batcher queue depths, per-shard counter-table
                            occupancy, flush-reason tallies, the slowest-N
                            decision flight recorder
    GET  /debug/top         tenant usage observatory: true top-K hottest
                            counters with namespace/limit/key attribution
                            and utilization (?k=N trims)
    GET  /debug/signals     unified ControlSignals snapshot + flattened
                            observation vector + ring timeline
    GET  /debug/pod         federated pod view: per-host ControlSignals
                            columns + min/max/sum rollups + the per-hop
                            forward breakdown (404 off pod mode)
    GET  /debug/events      typed pod event timeline: sequenced peer/
                            breaker/degraded/replay/hedge events
                            (?n=N trims, ?kind= filters; 404 off pod
                            mode)
    GET  /debug/pod/routing the pod ownership map an upstream load
                            balancer can learn: topology, per-host
                            shard blocks, pinned namespaces, routing
                            epoch (404 off pod mode)
    GET  /debug/capacity    the online serving-model observatory:
                            fitted coefficients, R², drift state,
                            SLO headroom, and what-if forecasts
                            (?batch=, ?lease_share=, ?procs=; 404
                            when the fit is off)
    GET  /debug/profile     jax.profiler capture status
    POST /debug/profile     {"action": "start"|"stop", "trace_dir"?: str}
                            toggles an on-demand jax.profiler trace
    GET  /debug/flight      flight-recorder incident bundles: list the
                            spool (?name= serves one bundle verbatim;
                            404 recorder off / unknown bundle)
    POST /debug/flight/trigger
                            fire a manual flight-recorder trigger:
                            freezes the exemplar rings, collects pod
                            peers' rings and persists a bundle
                            ({"note"?: str, "profile"?: bool})
    GET  /debug/tiering     tiered-storage state: per-tier resident
                            counts, migration/backlog accounting,
                            cold-decide latency and the model-priced
                            row costs (404 when --tier-mode off)
    GET  /debug/pod/standby warm-standby state: compiled kernel
                            buckets, warm-up seconds, join readiness
                            and time-to-first-decision (404 when
                            --standby off)
    POST /debug/pod/join    promote a warm standby into the pod:
                            {"address"} grows by one host; adding
                            "replace": <dead id> re-points a dead
                            member with zero slice movement (404 when
                            --pod-resize off)

POST bodies are CheckAndReportInfo: {"namespace", "values": {str: str},
"delta", "response_headers": optional "DRAFT_VERSION_03"}
(request_types.rs:10-16).
"""

from __future__ import annotations

import asyncio
import json
import math
from typing import Optional

from aiohttp import web

from ..core.cel import Context
from ..core.limit import Limit
from ..observability.device_plane import (
    JaxProfiler,
    ProfilerStateError,
    collect_debug_stats,
)
from ..observability.metrics import PrometheusMetrics
from ..observability.metrics_layer import installed as _metrics_layer_installed
from ..storage.base import StorageError
from .rls import RATE_LIMIT_HEADERS_DRAFT03

__all__ = [
    "make_http_app",
    "run_http_server",
    "DEBUG_STATS_SECTIONS",
    "DEBUG_SOURCE_SECTIONS",
]

#: /debug/stats sections sourced from debug_sources by named callable:
#: (section key, source attribute). Adding a pair here both serves the
#: section and registers it — tools/lint.py's debug-section cross-check
#: fails on a section served outside DEBUG_STATS_SECTIONS.
DEBUG_SOURCE_SECTIONS = (
    ("native_telemetry", "native_telemetry"),
    ("slo", "slo_status"),
    ("device_backed", "device_backed"),
    ("tenant_usage", "tenant_usage"),
    ("signals", "signals_debug"),
    ("pod", "pod_debug"),
    ("pod_events", "events_debug"),
    # pod fast path (ISSUE 13): the ownership map an upstream LB can
    # learn (topology, shard blocks, pinned namespaces, epoch)
    ("pod_routing", "routing_debug"),
    # serving-model observatory (ISSUE 14): fitted coefficients, R²,
    # drift state and SLO headroom (GET /debug/capacity adds what-ifs)
    ("capacity", "capacity_debug"),
    # elastic pod (ISSUE 15): the live-resize state machine —
    # transition state, received-slice ledger, topology epoch
    ("pod_resize", "resize_debug"),
    # warm standby (ISSUE 18): warm-up state (compiled kernel buckets,
    # warm seconds) and join readiness / time-to-first-decision
    ("standby", "standby_debug"),
    # flight recorder (ISSUE 16): exemplar-ring occupancy, trigger
    # tallies, pending peer retries and the bundle spool
    ("flight", "flight_debug"),
    # tiered storage (ISSUE 17): per-tier residency, migration rounds,
    # cold-decide latency and the model-priced row costs
    ("tiering", "tiering_debug"),
    # capacity controller (ISSUE 20): mode, knob values/specs, the
    # decision ring, membership clocks and interlock tallies
    ("controller", "controller_debug"),
)

#: every /debug/stats section THIS module can add on top of
#: collect_debug_stats' base payload. tools/lint.py cross-checks it both
#: ways against the actual handler code (every ``stats["..."] =``
#: literal and every DEBUG_SOURCE_SECTIONS key must be registered here,
#: and every registered name must be served) — a renamed or orphaned
#: section fails the gate instead of silently vanishing from the
#: endpoint its dashboards and benches scrape.
DEBUG_STATS_SECTIONS = (
    "profiler",
    "native_build",
    "native_hot_lane",
    "lease",
    "native_telemetry",
    "slo",
    "device_backed",
    "tenant_usage",
    "signals",
    "pod",
    "pod_events",
    "pod_routing",
    "capacity",
    "pod_resize",
    "standby",
    "flight",
    "tiering",
    "controller",
)


def _limit_dto(limit: Limit) -> dict:
    d = {
        "id": limit.id,
        "namespace": str(limit.namespace),
        "max_value": limit.max_value,
        "seconds": limit.seconds,
        "name": limit.name,
        "conditions": sorted(c.source for c in limit.conditions),
        "variables": sorted(v.source for v in limit.variables),
    }
    if limit.policy != "fixed_window":
        # Reference DTOs (request_types.rs:18-97) have no policy field;
        # emitted only for the token-bucket extension so fixed-window
        # payloads stay byte-identical.
        d["policy"] = limit.policy
    return d


def _counter_dto(counter) -> dict:
    return {
        "limit": _limit_dto(counter.limit),
        "set_variables": dict(counter.set_variables),
        "remaining": counter.remaining,
        "expires_in_seconds": (
            int(counter.expires_in) if counter.expires_in is not None else None
        ),
    }


def _openapi_spec() -> dict:
    """OpenAPI 3 document mirroring the reference's paperclip spec surface
    (request_types.rs:10-97, http_api/server.rs:77-260)."""
    limit_schema = {
        "type": "object",
        "required": ["namespace", "max_value", "seconds"],
        "properties": {
            "id": {"type": "string", "nullable": True},
            "namespace": {"type": "string"},
            "max_value": {"type": "integer", "format": "int64"},
            "seconds": {"type": "integer", "format": "int64"},
            "name": {"type": "string", "nullable": True},
            "conditions": {"type": "array", "items": {"type": "string"}},
            "variables": {"type": "array", "items": {"type": "string"}},
        },
    }
    counter_schema = {
        "type": "object",
        "properties": {
            "limit": {"$ref": "#/components/schemas/Limit"},
            "set_variables": {
                "type": "object",
                "additionalProperties": {"type": "string"},
            },
            "remaining": {
                "type": "integer", "format": "int64", "nullable": True,
            },
            "expires_in_seconds": {
                "type": "number", "nullable": True,
            },
        },
    }
    info_schema = {
        "type": "object",
        "required": ["namespace", "values"],
        "properties": {
            "namespace": {"type": "string"},
            "values": {
                "type": "object",
                "additionalProperties": {"type": "string"},
            },
            "delta": {"type": "integer", "format": "int64"},
            "response_headers": {
                "type": "string",
                "nullable": True,
                "enum": [None, "none", "draft_version_03"],
            },
        },
    }
    check_responses = {
        "200": {"description": "not rate limited"},
        "429": {"description": "rate limited"},
        "500": {"description": "storage error"},
    }
    ns_param = {
        "name": "namespace",
        "in": "path",
        "required": True,
        "schema": {"type": "string"},
    }
    info_body = {
        "required": True,
        "content": {
            "application/json": {
                "schema": {"$ref": "#/components/schemas/CheckAndReportInfo"}
            }
        },
    }
    return {
        "openapi": "3.0.3",
        "info": {
            "title": "Limitador server endpoint",
            "version": "1.0.0",
        },
        "paths": {
            "/status": {
                "get": {
                    "summary": "Health / config status",
                    "responses": {"200": {"description": "running"}},
                }
            },
            "/metrics": {
                "get": {
                    "summary": "Prometheus metrics",
                    "responses": {
                        "200": {"description": "prometheus exposition"}
                    },
                }
            },
            "/debug/stats": {
                "get": {
                    "summary": "Device-plane debug state (queues, shard "
                               "occupancy, plan-cache stats, flight "
                               "recorder)",
                    "responses": {
                        "200": {"description": "debug stats"}
                    },
                }
            },
            "/debug/top": {
                "get": {
                    "summary": "Tenant usage observatory: top-K hottest "
                               "counters with namespace/limit/key "
                               "attribution and utilization",
                    "responses": {
                        "200": {"description": "top counters"},
                        "404": {"description": "observatory not running"},
                    },
                }
            },
            "/debug/signals": {
                "get": {
                    "summary": "Unified control-signal snapshot (queue "
                               "wait, batch fill, breaker, sheds, lease "
                               "outstanding, native p99s, SLO burn, "
                               "calibration) + ring timeline",
                    "responses": {
                        "200": {"description": "control signals"},
                        "404": {"description": "signal bus not running"},
                    },
                }
            },
            "/debug/pod": {
                "get": {
                    "summary": "Federated pod view: per-host "
                               "ControlSignals columns, min/max/sum "
                               "rollups, and the per-hop forward "
                               "breakdown",
                    "responses": {
                        "200": {"description": "pod snapshot"},
                        "404": {"description": "not a pod"},
                    },
                }
            },
            "/debug/pod/routing": {
                "get": {
                    "summary": "Pod ownership map for upstream load "
                               "balancers: topology, per-host shard "
                               "blocks, pinned namespaces, routing "
                               "epoch",
                    "responses": {
                        "200": {"description": "ownership map"},
                        "404": {"description": "not a pod"},
                    },
                }
            },
            "/debug/pod/resize": {
                "get": {
                    "summary": "Elastic pod: the live membership-"
                               "transition state machine (epochs, "
                               "moved slices, received ledger)",
                    "responses": {
                        "200": {"description": "resize status"},
                        "404": {"description": "not a pod or "
                                               "--pod-resize off"},
                    },
                },
                "post": {
                    "summary": "Drive a live pod resize: {hosts: N, "
                               "peers: {id: addr}} migrates owned "
                               "slices epoch-gated with zero lost "
                               "updates; aborts revert to the old "
                               "topology",
                    "responses": {
                        "200": {"description": "transition complete"},
                        "400": {"description": "malformed proposal"},
                        "404": {"description": "not a pod or "
                                               "--pod-resize off"},
                        "409": {"description": "refused or aborted"},
                    },
                },
            },
            "/debug/pod/standby": {
                "get": {
                    "summary": "Warm standby: warm-up state (compiled "
                               "kernel buckets, seconds), join "
                               "readiness and time-to-first-decision",
                    "responses": {
                        "200": {"description": "standby status"},
                        "404": {"description": "not a warm standby"},
                    },
                }
            },
            "/debug/pod/join": {
                "post": {
                    "summary": "Promote a warm standby into the pod: "
                               "{address} grows by one host; {address, "
                               "replace: id} re-points a dead member "
                               "with zero slice movement",
                    "responses": {
                        "200": {"description": "join complete"},
                        "400": {"description": "malformed request"},
                        "404": {"description": "not a pod or "
                                               "--pod-resize off"},
                        "409": {"description": "refused or aborted"},
                    },
                }
            },
            "/debug/capacity": {
                "get": {
                    "summary": "Online serving-model observatory: "
                               "fitted coefficients, R², drift state, "
                               "SLO headroom and what-if forecasts "
                               "(?batch=, ?lease_share=, ?procs=)",
                    "responses": {
                        "200": {"description": "capacity forecast"},
                        "404": {"description": "model fit not running"},
                    },
                }
            },
            "/debug/events": {
                "get": {
                    "summary": "Typed pod event timeline (peer health, "
                               "breaker, degraded window, journal "
                               "replay, routing epoch, hedges), "
                               "sequenced per host",
                    "responses": {
                        "200": {"description": "pod events"},
                        "404": {"description": "not a pod"},
                    },
                }
            },
            "/debug/profile": {
                "get": {
                    "summary": "jax.profiler capture status",
                    "responses": {"200": {"description": "profiler status"}},
                },
                "post": {
                    "summary": "Start/stop an on-demand jax.profiler trace",
                    "requestBody": {
                        "required": True,
                        "content": {
                            "application/json": {
                                "schema": {
                                    "$ref": "#/components/schemas"
                                            "/ProfileAction"
                                }
                            }
                        },
                    },
                    "responses": {
                        "200": {"description": "profiler toggled"},
                        "409": {"description": "capture already active / "
                                               "not active"},
                    },
                },
            },
            "/debug/flight": {
                "get": {
                    "summary": "Flight-recorder incident bundles: list "
                               "the retention-capped spool, or serve "
                               "one self-contained bundle verbatim "
                               "(?name=)",
                    "responses": {
                        "200": {"description": "bundle list or bundle"},
                        "404": {"description": "recorder off / unknown "
                                               "bundle"},
                    },
                }
            },
            "/debug/flight/trigger": {
                "post": {
                    "summary": "Fire a manual flight-recorder trigger: "
                               "freeze the exemplar rings, collect pod "
                               "peers' rings for the same window, "
                               "persist an incident bundle",
                    "requestBody": {
                        "required": False,
                        "content": {
                            "application/json": {
                                "schema": {
                                    "type": "object",
                                    "properties": {
                                        "note": {
                                            "type": "string",
                                            "nullable": True,
                                        },
                                        "profile": {
                                            "type": "boolean",
                                            "default": False,
                                        },
                                    },
                                }
                            }
                        },
                    },
                    "responses": {
                        "200": {"description": "bundle persisted"},
                        "404": {"description": "recorder off"},
                    },
                }
            },
            "/limits/{namespace}": {
                "get": {
                    "summary": "Limits configured for a namespace",
                    "parameters": [ns_param],
                    "responses": {
                        "200": {
                            "description": "limits",
                            "content": {
                                "application/json": {
                                    "schema": {
                                        "type": "array",
                                        "items": {
                                            "$ref": "#/components/schemas/Limit"
                                        },
                                    }
                                }
                            },
                        }
                    },
                }
            },
            "/counters/{namespace}": {
                "get": {
                    "summary": "Live counters of a namespace",
                    "parameters": [ns_param],
                    "responses": {
                        "200": {
                            "description": "counters",
                            "content": {
                                "application/json": {
                                    "schema": {
                                        "type": "array",
                                        "items": {
                                            "$ref": "#/components/schemas/Counter"
                                        },
                                    }
                                }
                            },
                        }
                    },
                }
            },
            "/check": {
                "post": {
                    "summary": "Check only (no counter update)",
                    "requestBody": info_body,
                    "responses": check_responses,
                }
            },
            "/report": {
                "post": {
                    "summary": "Update counters only (no check)",
                    "requestBody": info_body,
                    "responses": {
                        "200": {"description": "counters updated"},
                        "500": {"description": "storage error"},
                    },
                }
            },
            "/check_and_report": {
                "post": {
                    "summary": "Check and update atomically",
                    "requestBody": info_body,
                    "responses": check_responses,
                }
            },
        },
        "components": {
            "schemas": {
                "Limit": limit_schema,
                "Counter": counter_schema,
                "CheckAndReportInfo": info_schema,
                "ProfileAction": {
                    "type": "object",
                    "required": ["action"],
                    "properties": {
                        "action": {
                            "type": "string",
                            "enum": ["start", "stop"],
                        },
                        "trace_dir": {"type": "string", "nullable": True},
                    },
                },
            }
        },
    }


class _Api:
    def __init__(
        self,
        limiter,
        metrics: Optional[PrometheusMetrics],
        status,
        debug_sources=None,
        profiler: Optional[JaxProfiler] = None,
        admission=None,
    ):
        self.limiter = limiter
        self.metrics = metrics
        # Admission controller: overload/priority shedding on the HTTP
        # decision path (None = pre-admission-plane behavior).
        self.admission = admission
        self.status = status or {}
        # Objects walked for /debug/stats device-plane state; the limiter
        # is always included (it reaches the batchers + device tables).
        self.debug_sources = [limiter] + list(debug_sources or ())
        self.profiler = profiler or JaxProfiler()
        from ..observability.metrics import storage_self_timed

        self._self_timed = storage_self_timed(limiter)

    async def _call(self, thunk, batched: bool = False):
        """Invoke (and await if needed) under a datastore-latency span; the
        thunk defers sync-limiter work into the timed region. With a
        MetricsLayer installed the wrapper stands down — in the reference
        the HTTP handlers carry non-aggregate span names
        (http_api/server.rs:82-185), so only the should_rate_limit and
        flush aggregates feed datastore_latency. ``batched`` marks
        operations the batched storages time themselves (queue excluded)
        — only those skip the wrapper; inline admin/read paths keep
        their wall-clock sample either way."""
        if _metrics_layer_installed() is not None:
            value = thunk()
            if asyncio.iscoroutine(value):
                return await value
            return value
        if self.metrics is not None and not (batched and self._self_timed):
            with self.metrics.time_datastore():
                value = thunk()
                if asyncio.iscoroutine(value):
                    return await value
                return value
        value = thunk()
        if asyncio.iscoroutine(value):
            return await value
        return value

    # -- handlers ----------------------------------------------------------

    async def get_status(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", **self.status})

    async def get_spec(self, request: web.Request) -> web.Response:
        """OpenAPI document for the admin/check API (the reference serves
        a paperclip-generated spec at /api/spec,
        http_api/server.rs:282-330)."""
        return web.json_response(_openapi_spec())

    async def get_metrics(self, request: web.Request) -> web.Response:
        if self.metrics is None:
            return web.Response(body=b"", content_type="text/plain")
        body = self.metrics.render()
        # OpenMetrics exposition (exemplars armed) carries its own
        # content type; headers= keeps the full parameterized value.
        return web.Response(
            body=body,
            headers={"Content-Type": self.metrics.content_type},
        )

    async def get_debug_stats(self, request: web.Request) -> web.Response:
        """Device-plane state without a debugger: queue depths, per-shard
        table occupancy, flush reasons, decision-plan cache stats, the
        slow-decision flight recorder, per-library native build state
        (compiler errors surface here, not just in logs) and the
        profiler state."""
        stats = collect_debug_stats(*self.debug_sources)
        stats["profiler"] = self.profiler.status()
        try:
            from ..native.build import build_status

            stats["native_build"] = build_status()
        except Exception:
            pass  # a diagnostics surface must never 500 the endpoint
        for source in self.debug_sources:
            lane_stats = getattr(source, "lane_stats", None)
            if callable(lane_stats):
                try:
                    lane = lane_stats()
                except Exception:
                    lane = None
                if lane:
                    stats["native_hot_lane"] = lane
                    break
        for source in self.debug_sources:
            lease_stats = getattr(source, "lease_stats", None)
            if callable(lease_stats):
                try:
                    lease = lease_stats()
                except Exception:
                    lease = None
                if lease:
                    stats["lease"] = lease
                    break
        # Sections sourced from debug_sources by named callable: the
        # native telemetry plane / SLO watchdog / device_backed probe,
        # the tenant usage observatory, and the control-signal bus —
        # each independent so a partial deployment still reports what
        # it has (the registry tuple is the lint-checked contract).
        for key, attr in DEBUG_SOURCE_SECTIONS:
            source_fn = self._debug_source_fn(attr)
            if source_fn is not None:
                try:
                    stats[key] = source_fn()
                except Exception:
                    pass  # diagnostics must never 500 the endpoint
        return web.json_response(stats)

    def _debug_source_fn(self, attr: str):
        """First debug source exposing a callable ``attr``."""
        for source in self.debug_sources:
            fn = getattr(source, attr, None)
            if callable(fn):
                return fn
        return None

    async def get_debug_top(self, request: web.Request) -> web.Response:
        """Tenant usage observatory: the true top-K hottest counters
        with namespace/limit/key attribution and utilization (drains
        the device accumulator first, so nothing is in flight)."""
        fn = self._debug_source_fn("top_counters")
        if fn is None:
            return web.json_response(
                {"error": "tenant usage observatory not running (tpu "
                          "storage only)"},
                status=404,
            )
        try:
            k = int(request.query["k"]) if "k" in request.query else None
        except ValueError:
            return web.json_response(
                {"error": "k must be an integer"}, status=400
            )
        return web.json_response(fn(k))

    async def get_debug_signals(self, request: web.Request) -> web.Response:
        """Unified control-signal bus: the current ControlSignals
        snapshot, its flattened observation vector, and the ring
        timeline."""
        fn = self._debug_source_fn("signals_debug")
        if fn is None:
            return web.json_response(
                {"error": "signal bus not running"}, status=404
            )
        return web.json_response(fn())

    async def get_debug_tiering(self, request: web.Request) -> web.Response:
        """Tiered-storage state (ISSUE 17): per-tier resident counts,
        the TierManager's migration/backlog accounting, cold-decide
        latency percentiles and the model-priced per-row costs the
        promotion/demotion pricing used last round."""
        fn = self._debug_source_fn("tiering_debug")
        if fn is None:
            return web.json_response(
                {"error": "tiered storage not enabled (--tier-mode on)"},
                status=404,
            )
        return web.json_response(fn())

    async def get_debug_pod(self, request: web.Request) -> web.Response:
        """Federated pod observability view: per-host ControlSignals
        columns with min/max/sum rollups, column ages, the signal
        timeline and this host's per-hop forward breakdown."""
        fn = self._debug_source_fn("pod_debug")
        if fn is None:
            return web.json_response(
                {"error": "not a pod (single-host deployment)"},
                status=404,
            )
        return web.json_response(fn())

    async def get_debug_pod_routing(
        self, request: web.Request
    ) -> web.Response:
        """The routing truth an upstream LB can learn (ISSUE 13):
        topology, per-host contiguous shard blocks, the pinned-
        namespace map and the routing epoch — enough to send a
        descriptor straight to its owner host (an Envoy ring-hash on
        descriptor keys approximates it; this map is the exact
        verdict)."""
        fn = self._debug_source_fn("routing_debug")
        if fn is None:
            return web.json_response(
                {"error": "not a pod (single-host deployment)"},
                status=404,
            )
        return web.json_response(fn())

    def _resize_coordinator(self):
        fn = self._debug_source_fn("resize_debug")
        if fn is None:
            return None, web.json_response(
                {"error": "not a pod (single-host deployment)"},
                status=404,
            )
        out = fn()
        if not out.get("armed"):
            return None, web.json_response(
                {"error": "pod resize not armed (--pod-resize off)"},
                status=404,
            )
        return out, None

    async def get_debug_pod_resize(
        self, request: web.Request
    ) -> web.Response:
        """The elastic-membership state machine (ISSUE 15): the live
        transition (state, epochs, moved slices), the received-slice
        ledger and cumulative resize counters."""
        out, err = self._resize_coordinator()
        if err is not None:
            return err
        return web.json_response(out)

    async def post_debug_pod_resize(
        self, request: web.Request
    ) -> web.Response:
        """Drive a LIVE membership transition: ``{"hosts": N,
        "peers": {"2": "host:port", ...}}`` resizes the running pod to
        N hosts (peers must name every member the coordinator does not
        already know). Blocks until the transition completes or aborts;
        an abort reverts to the old topology with nothing lost
        (docs/configuration.md, "Elastic pod")."""
        _out, err = self._resize_coordinator()
        if err is not None:
            return err
        try:
            data = await request.json()
            hosts = int(data["hosts"])
            peers = {
                int(h): str(a)
                for h, a in (data.get("peers") or {}).items()
            }
        except (KeyError, ValueError, TypeError) as exc:
            return web.json_response(
                {"error": f"bad request: {exc}"}, status=400
            )
        resize_fn = self._debug_source_fn("pod_resize_admin")
        loop = asyncio.get_running_loop()
        try:
            out = await loop.run_in_executor(
                None, lambda: resize_fn(hosts, peers)
            )
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=409)
        except StorageError as exc:
            return web.json_response({"error": str(exc)}, status=500)
        return web.json_response(out, status=200 if out.get("ok") else 409)

    async def get_debug_pod_standby(
        self, request: web.Request
    ) -> web.Response:
        """Warm-standby state (ISSUE 18): warm-up progress (compiled
        kernel buckets, seconds), join readiness and — after a
        promotion — the joiner's time-to-first-decision."""
        fn = self._debug_source_fn("standby_debug")
        out = fn() if fn is not None else None
        if out is None or not out.get("armed"):
            return web.json_response(
                {"error": "not a warm standby (--standby off)"},
                status=404,
            )
        return web.json_response(out)

    async def post_debug_pod_join(
        self, request: web.Request
    ) -> web.Response:
        """Promote a warm standby into the running pod:
        ``{"address": "host:port"}`` grows the pod by one host (the
        standby becomes the next host id); ``{"address": ...,
        "replace": <dead id>}`` re-points a dead member's host id at
        the standby with zero slice movement. Blocks until the join
        completes or aborts (docs/configuration.md, "Warm standby &
        fast join")."""
        _out, err = self._resize_coordinator()
        if err is not None:
            return err
        try:
            data = await request.json()
            address = str(data["address"])
            replace = data.get("replace")
            if replace is not None:
                replace = int(replace)
            seed_plans = bool(data.get("seed_plans", True))
        except (KeyError, ValueError, TypeError) as exc:
            return web.json_response(
                {"error": f"bad request: {exc}"}, status=400
            )
        join_fn = self._debug_source_fn("pod_join_admin")
        loop = asyncio.get_running_loop()
        try:
            out = await loop.run_in_executor(
                None,
                lambda: join_fn(
                    address, replace=replace, seed_plans=seed_plans
                ),
            )
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=409)
        except StorageError as exc:
            return web.json_response({"error": str(exc)}, status=500)
        return web.json_response(out, status=200 if out.get("ok") else 409)

    async def get_debug_capacity(
        self, request: web.Request
    ) -> web.Response:
        """The serving-model observatory (ISSUE 14): fitted
        coefficients, R², drift state, SLO headroom, and what-if
        forecasts — ``?batch=`` overrides the batch size,
        ``?lease_share=`` the lease coverage, ``?procs=`` the
        host count."""
        fn = self._debug_source_fn("capacity_debug")
        if fn is None:
            return web.json_response(
                {"error": "serving-model fit not running "
                          "(--model-fit off or host-only storage)"},
                status=404,
            )
        kwargs: dict = {}
        try:
            if "batch" in request.query:
                kwargs["batch"] = int(request.query["batch"])
                if kwargs["batch"] < 1:
                    raise ValueError
            if "lease_share" in request.query:
                kwargs["lease_share"] = float(
                    request.query["lease_share"]
                )
                # float() happily parses nan/inf, which would ride the
                # clamp into the features and serialize as bare NaN —
                # invalid JSON for any strict client
                if not math.isfinite(kwargs["lease_share"]):
                    raise ValueError
            if "procs" in request.query:
                kwargs["procs"] = int(request.query["procs"])
                if kwargs["procs"] < 1:
                    raise ValueError
        except ValueError:
            return web.json_response(
                {"error": "batch and procs must be positive integers, "
                          "lease_share a finite float"},
                status=400,
            )
        return web.json_response(fn(**kwargs))

    async def get_debug_events(self, request: web.Request) -> web.Response:
        """The typed pod event timeline (?n=N trims to the most recent
        N, ?kind= filters to one event kind); mergeable pod-wide by
        (host, seq)."""
        fn = self._debug_source_fn("events_debug")
        if fn is None:
            return web.json_response(
                {"error": "not a pod (single-host deployment)"},
                status=404,
            )
        try:
            n = int(request.query["n"]) if "n" in request.query else None
        except ValueError:
            return web.json_response(
                {"error": "n must be an integer"}, status=400
            )
        return web.json_response(
            fn(n=n, kind=request.query.get("kind"))
        )

    async def get_debug_profile(self, request: web.Request) -> web.Response:
        return web.json_response(self.profiler.status())

    async def post_debug_profile(self, request: web.Request) -> web.Response:
        try:
            data = await request.json()
            action = data["action"]
            trace_dir = data.get("trace_dir")
            if action not in ("start", "stop"):
                raise ValueError(f"unknown action {action!r}")
            if trace_dir is not None and not isinstance(trace_dir, str):
                raise ValueError("trace_dir must be a string")
        except (KeyError, ValueError, TypeError) as exc:
            return web.json_response(
                {"error": f"bad request: {exc}"}, status=400
            )
        try:
            if action == "start":
                target = self.profiler.start(trace_dir)
                return web.json_response(
                    {"status": "started", "trace_dir": target}
                )
            target = self.profiler.stop()
            return web.json_response(
                {"status": "stopped", "trace_dir": target}
            )
        except ProfilerStateError as exc:
            return web.json_response({"error": str(exc)}, status=409)
        except Exception as exc:  # jax.profiler failures must not crash
            return web.json_response({"error": str(exc)}, status=500)

    async def get_debug_flight(self, request: web.Request) -> web.Response:
        """The flight-recorder bundle spool: the list of persisted
        incident bundles (newest first), or — with ``?name=`` — one
        self-contained bundle verbatim for offline autopsy."""
        list_fn = self._debug_source_fn("flight_bundles")
        if list_fn is None:
            return web.json_response(
                {"error": "flight recorder not running (--flight off)"},
                status=404,
            )
        name = request.query.get("name")
        if name is None:
            return web.json_response({"bundles": list_fn()})
        read_fn = self._debug_source_fn("flight_bundle")
        bundle = read_fn(name) if read_fn is not None else None
        if bundle is None:
            return web.json_response(
                {"error": f"unknown bundle {name!r}"}, status=404
            )
        return web.json_response(bundle)

    async def post_debug_flight_trigger(
        self, request: web.Request
    ) -> web.Response:
        """Fire a manual flight-recorder trigger (``{"note"?: str,
        "profile"?: bool}``): freezes the exemplar rings, asks pod
        peers for their rings over the same window, and persists a
        self-contained incident bundle. Runs off-loop — the peer
        collection is blocking control-plane RPC."""
        fn = self._debug_source_fn("flight_trigger")
        if fn is None:
            return web.json_response(
                {"error": "flight recorder not running (--flight off)"},
                status=404,
            )
        note, profile = None, False
        if request.can_read_body:
            try:
                data = await request.json()
                note = data.get("note")
                profile = bool(data.get("profile", False))
                if note is not None and not isinstance(note, str):
                    raise ValueError("note must be a string")
            except ValueError as exc:
                return web.json_response(
                    {"error": f"bad request: {exc}"}, status=400
                )
        loop = asyncio.get_running_loop()
        try:
            out = await loop.run_in_executor(
                None, lambda: fn(note, profile)
            )
        except Exception as exc:  # diagnostics must never 500 opaquely
            return web.json_response({"error": str(exc)}, status=500)
        return web.json_response(out)

    async def get_limits(self, request: web.Request) -> web.Response:
        ns = request.match_info["namespace"]
        limits = self.limiter.get_limits(ns)
        return web.json_response([_limit_dto(l) for l in sorted(limits)])

    async def get_counters(self, request: web.Request) -> web.Response:
        ns = request.match_info["namespace"]
        try:
            counters = await self._call(lambda: self.limiter.get_counters(ns))
        except StorageError as exc:
            return web.json_response({"error": str(exc)}, status=500)
        dtos = sorted(
            (_counter_dto(c) for c in counters),
            key=lambda d: json.dumps(d, sort_keys=True),
        )
        return web.json_response(dtos)

    @staticmethod
    def _parse_info(data) -> tuple:
        namespace = data["namespace"]
        values = data.get("values") or {}
        delta = int(data.get("delta", 1))
        if delta < 0:
            # The reference's DTO declares delta: u64 (request_types.rs:14);
            # a negative delta would decrement counters and defeat limits.
            raise ValueError("delta must be >= 0")
        response_headers = data.get("response_headers")
        ctx = Context()
        ctx.list_binding("descriptors", [dict(values)])
        return namespace, ctx, delta, response_headers

    async def post_check(self, request: web.Request) -> web.Response:
        try:
            data = await request.json()
            namespace, ctx, delta, _ = self._parse_info(data)
        except (KeyError, ValueError, TypeError) as exc:
            return web.json_response({"error": f"bad request: {exc}"}, status=400)
        try:
            result = await self._call(
                lambda: self.limiter.is_rate_limited(namespace, ctx, delta)
            )
        except StorageError as exc:
            return web.json_response({"error": str(exc)}, status=500)
        if result.limited:
            return web.Response(status=429)
        return web.Response(status=200)

    async def post_report(self, request: web.Request) -> web.Response:
        try:
            data = await request.json()
            namespace, ctx, delta, _ = self._parse_info(data)
        except (KeyError, ValueError, TypeError) as exc:
            return web.json_response({"error": f"bad request: {exc}"}, status=400)
        try:
            await self._call(
                lambda: self.limiter.update_counters(namespace, ctx, delta),
                batched=True,
            )
        except StorageError as exc:
            return web.json_response({"error": str(exc)}, status=500)
        return web.Response(status=200)

    async def post_check_and_report(self, request: web.Request) -> web.Response:
        try:
            data = await request.json()
            namespace, ctx, delta, response_headers = self._parse_info(data)
        except (KeyError, ValueError, TypeError) as exc:
            return web.json_response({"error": f"bad request: {exc}"}, status=400)
        want_headers = response_headers == RATE_LIMIT_HEADERS_DRAFT03
        ticket = None
        if self.admission is not None:
            from ..admission.controller import AdmissionShed

            try:
                # The HTTP surface carries no deadline; overload and
                # priority shedding still apply (429 for the over-limit
                # semantics, 503 for unavailable — the reference's
                # storage-error status on this path is 500, but a shed
                # is an explicit backpressure signal, not a failure).
                ticket = self.admission.admit(
                    namespace, data.get("values") or {}
                )
            except AdmissionShed as shed:
                if shed.overlimit:
                    return web.Response(status=429)
                return web.json_response(
                    {"error": str(shed)}, status=503
                )
        try:
            result = await self._call(
                lambda: self.limiter.check_rate_limited_and_update(
                    namespace, ctx, delta, want_headers
                ),
                batched=True,
            )
        except StorageError as exc:
            return web.json_response({"error": str(exc)}, status=500)
        finally:
            if ticket is not None:
                ticket.release()
        headers = result.response_header() if want_headers else {}
        if self.metrics:
            extra = self.metrics.custom_labels(ctx)
        if result.limited:
            if self.metrics:
                self.metrics.incr_limited_calls(
                    namespace, result.limit_name, labels=extra
                )
            return web.Response(status=429, headers=headers)
        if self.metrics:
            self.metrics.incr_authorized_calls(namespace, labels=extra)
            self.metrics.incr_authorized_hits(namespace, delta, labels=extra)
        return web.Response(status=200, headers=headers)


def make_http_app(
    limiter,
    metrics: Optional[PrometheusMetrics] = None,
    status: Optional[dict] = None,
    debug_sources=None,
    profiler: Optional[JaxProfiler] = None,
    admission=None,
) -> web.Application:
    from .middleware import http_request_id_middleware

    api = _Api(limiter, metrics, status, debug_sources, profiler, admission)
    app = web.Application(middlewares=[http_request_id_middleware])
    app.router.add_get("/status", api.get_status)
    app.router.add_get("/api/spec", api.get_spec)
    app.router.add_get("/metrics", api.get_metrics)
    app.router.add_get("/debug/stats", api.get_debug_stats)
    app.router.add_get("/debug/top", api.get_debug_top)
    app.router.add_get("/debug/signals", api.get_debug_signals)
    app.router.add_get("/debug/pod", api.get_debug_pod)
    app.router.add_get("/debug/pod/routing", api.get_debug_pod_routing)
    app.router.add_get("/debug/pod/resize", api.get_debug_pod_resize)
    app.router.add_post("/debug/pod/resize", api.post_debug_pod_resize)
    app.router.add_get("/debug/pod/standby", api.get_debug_pod_standby)
    app.router.add_post("/debug/pod/join", api.post_debug_pod_join)
    app.router.add_get("/debug/capacity", api.get_debug_capacity)
    app.router.add_get("/debug/events", api.get_debug_events)
    app.router.add_get("/debug/profile", api.get_debug_profile)
    app.router.add_post("/debug/profile", api.post_debug_profile)
    app.router.add_get("/debug/flight", api.get_debug_flight)
    app.router.add_post("/debug/flight/trigger", api.post_debug_flight_trigger)
    app.router.add_get("/debug/tiering", api.get_debug_tiering)
    app.router.add_get("/limits/{namespace}", api.get_limits)
    app.router.add_get("/counters/{namespace}", api.get_counters)
    app.router.add_post("/check", api.post_check)
    app.router.add_post("/report", api.post_report)
    app.router.add_post("/check_and_report", api.post_check_and_report)
    return app


async def run_http_server(
    limiter,
    host: str = "0.0.0.0",
    port: int = 8080,
    metrics: Optional[PrometheusMetrics] = None,
    status: Optional[dict] = None,
    debug_sources=None,
    profiler: Optional[JaxProfiler] = None,
    admission=None,
) -> web.AppRunner:
    """Start the HTTP server (returns the runner; caller owns shutdown)."""
    app = make_http_app(
        limiter, metrics, status, debug_sources, profiler, admission
    )
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    return runner
