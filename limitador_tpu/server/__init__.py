from .http_api import make_http_app, run_http_server
from .limits_file import LimitsFileWatcher, load_limits_file
from .rls import RlsService, serve_rls

__all__ = [
    "make_http_app",
    "run_http_server",
    "LimitsFileWatcher",
    "load_limits_file",
    "RlsService",
    "serve_rls",
]
