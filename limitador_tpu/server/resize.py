"""Elastic pod: live resharding and membership change (ISSUE 15).

Membership used to be fixed at boot: growing a pod from 2 to 3 hosts
meant a stop-the-world redeploy that dropped every device-resident
counter. This module composes the machinery earlier PRs built — routing
epochs on ``PodRouter`` (PR 10/12), the failover delta journal +
``apply_deltas`` reconcile (PR 2/11), the PeerLane's resilience and the
typed pod event timeline — into a live ``resize``/``add_host``/
``drain_host`` on a RUNNING pod, the way BLITZSCALE/Maxwell (PAPERS.md)
treat capacity change as a first-class storage operation rather than an
outage.

The epoch-gated transition, per member host:

1. **prepare** — the initiator broadcasts the proposed topology + the
   full peer map; every member validates it is on the FROM epoch, adopts
   the union peer set (new hosts become dialable before any traffic
   re-routes) and arms per-owner degraded guards for them.
2. **commit** (``resize_begin`` then ``epoch_bump`` on the timeline) —
   every member retargets its router to the new topology at the
   protocol-agreed topology epoch. From this instant new arrivals route
   by the NEXT epoch; forwards still stamped with the old epoch are
   rejected with the typed rerouteable ``stale_epoch`` status and the
   origin re-plans (never decided by a wrong owner). A native pipeline
   is invalidated here, which recalls outstanding leases through the
   existing return ring (PR 6) and re-stamps the C mirror's ownership.
3. **migrate** (``migrate_begin``/``migrate_end`` per slice) — each
   host streams the table slices it owned under FROM but not under TO,
   slice-by-slice (slice = the key's global shard under TO), over the
   ``kind:"migrate"`` lane RPC. A migrate batch carries ABSOLUTE
   counter values; the receiver applies diffs against a per-transition
   ledger, which makes delivery idempotent under retry — a duplicated
   batch applies nothing. Convergence sweeps replay whatever accrued
   during the copy (the journal-replay step, expressed as value diffs),
   then a ``final`` marker releases the old slice.
4. **complete** (``resize_end``) — when every member reports its
   migration done, the initiator completes the transition and receivers
   drop their ledgers: the new owners are authoritative.

**Abort** (``resize_abort``) is the safety net when a host dies
mid-migration: every reachable member reverts its router to the FROM
topology (at a NEW agreed epoch — epochs only move forward), receivers
push back what they received-plus-admitted — full values for finalized
slices (the source already released), ``value - received`` deltas for
partial ones (the source kept its copy) — and guards' journals accrued
against members the revert removed are redistributed to their current
owners through ``apply_deltas``. The PR 11 degraded-owner failover is
what keeps answering during the window: a dead new owner's traffic
fails over to the local exact stand-in and is journaled, so zero
ADMITTED deltas are lost; accuracy across the window carries the same
bound as any degraded window (docs/serving-model.md, "The degraded
window during a resize").

``--pod-resize off`` (the default) never constructs a coordinator:
forward payloads, the serve path and every verdict are byte-identical
to PR 14 (test-pinned).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..routing import PodTopology, counter_key

__all__ = ["PodResizeCoordinator", "METRIC_FAMILIES"]

log = logging.getLogger("limitador_tpu.pod.resize")

#: metric families this subsystem owns (cross-checked against
#: observability/metrics.py by the analysis registry pass). The values
#: are merged into the pod frontend's library_stats: the coordinator
#: owns the transition counters, the lane owns the wrong-epoch gate
#: count and the frontend the in-band re-plans.
METRIC_FAMILIES = (
    "pod_resize_epoch",
    "pod_resize_active",
    "pod_resize_completed",
    "pod_resize_aborted",
    "pod_resize_slices_moved",
    "pod_resize_moved_deltas",
    "pod_resize_released_counters",
    "pod_resize_seconds",
    "pod_resize_stale_rejects",
    "pod_resize_replans",
    # fast join (ISSUE 18): warm-standby promotion through join_host —
    # counters on the initiator; ttfd/routed-share parity gauges are
    # stamped by the joiner when it answers its first decision
    "join_completed",
    "join_aborted",
    "join_seconds",
    "join_seed_entries",
    "join_ttfd_seconds",
)


def _owner_of(key: tuple, namespace: str, topology: PodTopology,
              pinned: Dict[str, int]) -> int:
    """Who serves this counter under a given (topology, pinned map):
    the pin host for pinned namespaces (their counters live there, not
    at their hash owner), the contiguous-block hash owner otherwise."""
    pin = pinned.get(namespace)
    return pin if pin is not None else topology.owner_host(key)


class _Transition:
    """One membership transition's per-host state machine:
    armed -> migrating -> done | failed | aborted | complete."""

    __slots__ = (
        "from_topology", "to_topology", "peers", "tepoch_from",
        "tepoch_to", "pinned_from", "pinned_to", "state", "error",
        "initiator", "started", "finished", "moved_slices",
        "moved_counters", "aborting",
    )

    def __init__(self, from_topology, to_topology, peers, tepoch_from,
                 tepoch_to, initiator):
        self.from_topology = from_topology
        self.to_topology = to_topology
        self.peers = dict(peers)
        self.tepoch_from = int(tepoch_from)
        self.tepoch_to = int(tepoch_to)
        self.pinned_from: Dict[str, int] = {}
        self.pinned_to: Dict[str, int] = {}
        self.state = "armed"
        self.error: Optional[str] = None
        self.initiator = int(initiator)
        self.started = time.time()
        self.finished: Optional[float] = None
        self.moved_slices = 0
        self.moved_counters = 0
        self.aborting = False

    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "from_hosts": self.from_topology.hosts,
            "to_hosts": self.to_topology.hosts,
            "tepoch_from": self.tepoch_from,
            "tepoch_to": self.tepoch_to,
            "initiator": self.initiator,
            "moved_slices": self.moved_slices,
            "moved_counters": self.moved_counters,
            "error": self.error,
            "started": round(self.started, 3),
            "seconds": round(
                (self.finished or time.time()) - self.started, 6
            ),
        }


class PodResizeCoordinator:
    """Drives (and answers) the elastic-membership protocol on one pod
    host. Wire with ``frontend.attach_resize(coordinator)``; the
    initiating host's :meth:`resize` is what the admin endpoint
    (``POST /debug/pod/resize``) calls."""

    #: bounded convergence sweeps per slice: sweep 2+ only ships what
    #: accrued during sweep 1's copy (post-bump the source admits
    #: nothing new for a moving key, so this converges immediately in
    #: practice; in-flight stragglers get one more round)
    MAX_SWEEPS = 4
    #: migrate RPC attempts per slice before the transition fails
    MIGRATE_RETRIES = 3
    #: rows per migrate RPC (the lane runs the default 4MB receive cap)
    CHUNK = 500

    def __init__(
        self,
        frontend,
        peers: Optional[Dict[int, str]] = None,
        listen_address: Optional[str] = None,
        migrate_timeout_s: float = 10.0,
        poll_interval_s: float = 0.05,
        transition_timeout_s: float = 60.0,
        slice_pause_s: float = 0.0,
    ):
        self.frontend = frontend
        self.lane = frontend.lane
        self.router = frontend.router
        self.host_id = int(self.lane.host_id)
        # full member address map INCLUDING this host (broadcast to
        # members so each can derive its own peer set)
        self._peers: Dict[int, str] = {
            int(h): str(a) for h, a in (peers or self.lane.peers).items()
        }
        if listen_address:
            self._peers[self.host_id] = str(listen_address)
        self.migrate_timeout_s = float(migrate_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.transition_timeout_s = float(transition_timeout_s)
        #: chaos hook (env TPU_POD_RESIZE_SLICE_PAUSE_MS): a pause
        #: between migrate_begin and the first copy of each slice, so a
        #: drill can SIGKILL a host deterministically mid-migration
        self.slice_pause_s = float(slice_pause_s)
        self._lock = threading.RLock()
        self._transition: Optional[_Transition] = None
        # True from resize() entry until its transition is installed
        # (or the proposal fails): self._transition only exists at
        # commit, so without this flag two concurrent resize() calls
        # would both pass the active check during the network-bound
        # prepare phase and race two transitions at colliding epochs.
        self._proposing = False
        # receiving-side ledger, per transition: slice -> {
        #   "rows": {key: (counter, received_value)}, "final": bool }
        self._received: Dict[int, dict] = {}
        self._watchdog: Optional[threading.Timer] = None
        # cumulative counters (the pod_resize_* family feed)
        self.completed = 0
        self.aborted = 0
        self.slices_moved = 0
        self.moved_deltas = 0
        self.released_counters = 0
        self.resize_seconds = 0.0
        # fast-join counters (ISSUE 18; the join_* family feed). The
        # initiator counts completions and shipped seed entries; the
        # joiner stamps join_ttfd_seconds when its first decision
        # answers after the adopt (0.0 = never joined / not a joiner).
        self.joins_completed = 0
        self.joins_aborted = 0
        self.join_seconds = 0.0
        self.join_seed_entries = 0
        self.join_ttfd_seconds = 0.0
        # set at join_admin "adopt" on the joiner; the first decision
        # after it resolves the ttfd gauge (frontend calls note_first_decision)
        self._join_adopted_at: Optional[float] = None

    # -- small accessors -------------------------------------------------------

    @property
    def active(self) -> bool:
        with self._lock:
            t = self._transition
            return t is not None and t.state in ("armed", "migrating")

    @property
    def busy(self) -> bool:
        """True from a resize/join proposal's entry until its
        transition commits or fails — the capacity controller's
        global actuation interlock (ISSUE 20): nothing may actuate
        while membership is in flight."""
        with self._lock:
            t = self._transition
            return self._proposing or (
                t is not None and t.state in ("armed", "migrating")
            )

    def _storage(self):
        storage = self.frontend._limiter.storage
        return getattr(storage, "counters", storage)

    def stale_info(self) -> dict:
        """What a stale_epoch rejection carries so a behind origin can
        adopt: the current topology geometry and the member map."""
        topo = self.router.topology
        return {
            "topology": {
                "hosts": topo.hosts,
                "shards_per_host": topo.shards_per_host,
            },
            "peers": {str(h): a for h, a in self._peers.items()},
        }

    # -- the initiating host ---------------------------------------------------

    def resize(
        self, hosts: int, peers: Optional[Dict[int, str]] = None,
        shards_per_host: Optional[int] = None,
    ) -> dict:
        """Drive a live membership transition to ``hosts`` (blocking;
        admin endpoint / drill threads — never a serving loop). Returns
        the transition summary; raises ValueError on a bad proposal.
        ``peers`` must map EVERY member of the union membership (old
        and new hosts) to its lane address; omitted entries fall back
        to the map the coordinator already knows."""
        hosts = int(hosts)
        old = self.router.topology
        if hosts < 1:
            raise ValueError("resize needs hosts >= 1")
        if self.host_id >= hosts:
            raise ValueError(
                "initiate a drain from a surviving host (this host "
                f"{self.host_id} leaves the {hosts}-host topology)"
            )
        if hosts == old.hosts:
            return {"ok": True, "noop": True, **self.status()}
        member_map = dict(self._peers)
        for h, addr in (peers or {}).items():
            member_map[int(h)] = str(addr)
        union = range(max(old.hosts, hosts))
        missing = [h for h in union if h not in member_map]
        if missing:
            raise ValueError(
                f"resize to {hosts} hosts needs a peer address for "
                f"every member; missing {missing}"
            )
        with self._lock:
            if self.active or self._proposing:
                raise ValueError("a pod resize is already in flight")
            self._proposing = True
            to_topo = PodTopology(
                hosts=hosts, host_id=self.host_id,
                shards_per_host=int(
                    shards_per_host or old.shards_per_host
                ),
            )
            transition = _Transition(
                old, to_topo, member_map,
                tepoch_from=self.router.topology_epoch,
                tepoch_to=self.router.topology_epoch + 1,
                initiator=self.host_id,
            )
        try:
            return self._drive(transition, union, member_map)
        finally:
            with self._lock:
                self._proposing = False

    def _drive(self, transition: _Transition, union, member_map) -> dict:
        hosts = transition.to_topology.hosts
        members = [h for h in union if h != self.host_id]
        plan = {
            "hosts": hosts,
            "shards_per_host": transition.to_topology.shards_per_host,
            "peers": {str(h): a for h, a in member_map.items()},
            "tepoch_from": transition.tepoch_from,
            "tepoch_to": transition.tepoch_to,
            "from": self.host_id,
        }
        old_peers = dict(self._peers)
        self._peers = member_map
        self.lane.set_peers(
            {h: a for h, a in member_map.items() if h != self.host_id}
        )
        self.frontend.ensure_guards()
        # phase 1: prepare — every member must be reachable and on the
        # FROM epoch before any routing changes anywhere. A refused
        # proposal rolls the peer map back: nothing may keep probing a
        # typo'd address or advertising a map no transition installed.
        for host in members:
            try:
                resp = self.lane.admin_call(
                    host, {"kind": "resize_admin", "op": "prepare", **plan},
                    timeout=self.migrate_timeout_s,
                )
            except Exception as exc:
                self._restore_peers(old_peers)
                raise ValueError(
                    f"pod host {host} unreachable at prepare: {exc}"
                ) from exc
            if not resp.get("ok"):
                self._restore_peers(old_peers)
                raise ValueError(
                    f"pod host {host} refused the resize: "
                    f"{resp.get('error')}"
                )
        # phase 2: commit — this host first (the initiator is the
        # reference epoch; stragglers' forwards re-plan in-band). A
        # member that refuses OR is unreachable aborts the transition
        # immediately — without the refusal check the pod would run
        # split-topology until the deadline.
        self._commit(transition)
        for host in members:
            err = None
            try:
                resp = self.lane.admin_call(
                    host, {"kind": "resize_admin", "op": "commit", **plan},
                    timeout=self.migrate_timeout_s,
                )
                if not resp.get("ok"):
                    err = f"refused commit: {resp.get('error')}"
            except Exception as exc:
                err = f"commit failed: {exc}"
            if err is not None:
                log.warning(
                    f"pod resize: host {host} {err}; aborting the "
                    "transition"
                )
                self._broadcast_abort(
                    transition, f"host {host} {err}"
                )
                return {"ok": False, "aborted": True, **self.status()}
        # phase 3: poll members (and ourselves) until every migration
        # is done, a member fails, or the transition deadline passes
        deadline = time.time() + self.transition_timeout_s
        pending = set(union)
        while time.time() < deadline:
            with self._lock:
                mine = transition.state
            if mine == "done":
                pending.discard(self.host_id)
            elif mine in ("failed", "aborted"):
                self._broadcast_abort(
                    transition, transition.error or "local migration failed"
                )
                return {"ok": False, "aborted": True, **self.status()}
            for host in list(pending - {self.host_id}):
                try:
                    resp = self.lane.admin_call(
                        host,
                        {
                            "kind": "resize_admin", "op": "status",
                            "tepoch_to": transition.tepoch_to,
                            "from": self.host_id,
                        },
                        timeout=self.migrate_timeout_s,
                    )
                except Exception:
                    continue  # transient; the deadline bounds us
                state = resp.get("state")
                if state == "done":
                    pending.discard(host)
                elif state in ("failed", "aborted"):
                    self._broadcast_abort(
                        transition,
                        f"host {host} migration {state}: "
                        f"{resp.get('error')}",
                    )
                    return {"ok": False, "aborted": True, **self.status()}
            if not pending:
                break
            time.sleep(self.poll_interval_s)
        if pending:
            self._broadcast_abort(
                transition,
                f"transition deadline: hosts {sorted(pending)} not done",
            )
            return {"ok": False, "aborted": True, **self.status()}
        # phase 4: complete — receivers drop their ledgers, the new
        # owners are authoritative
        self._complete(transition)
        for host in [h for h in union if h != self.host_id]:
            try:
                self.lane.admin_call(
                    host,
                    {
                        "kind": "resize_admin", "op": "complete",
                        "tepoch_to": transition.tepoch_to,
                        "from": self.host_id,
                    },
                    timeout=self.migrate_timeout_s,
                )
            except Exception as exc:
                # the member self-completes on its watchdog; harmless
                log.warning(
                    f"pod resize: complete to host {host} failed: {exc}"
                )
        return {"ok": True, **self.status()}

    def _restore_peers(self, old_peers: Dict[int, str]) -> None:
        """Roll a failed proposal's peer-map adoption back (before any
        commit, so there is no transition to abort)."""
        self._peers = dict(old_peers)
        self.lane.set_peers({
            h: a for h, a in old_peers.items() if h != self.host_id
        })

    def add_host(self, address: str) -> dict:
        """Grow the pod by one host (the next host id) at ``address``."""
        hosts = self.router.topology.hosts
        return self.resize(hosts + 1, peers={hosts: address})

    def drain_host(self) -> dict:
        """Shrink the pod by one host: the highest host id drains its
        slices to the survivors and leaves the topology. (Host ids are
        contiguous block offsets — only the tail host can leave.)"""
        hosts = self.router.topology.hosts
        if hosts <= 1:
            raise ValueError("cannot drain a single-host pod")
        return self.resize(hosts - 1)

    # -- fast join: warm-standby promotion (ISSUE 18) --------------------------

    def join_host(
        self,
        address: str,
        replace: Optional[int] = None,
        seed_plans: bool = True,
        max_seed_entries: int = 4096,
    ) -> dict:
        """Promote a warm standby at ``address`` into the pod, overlap
        its state ship with serving. Two modes:

        * grow (``replace=None``) — the standby becomes the next host
          id; after the ship this is exactly :meth:`add_host` (the PR 15
          migrate lane moves its shard slice, already overlapped with
          serving), so the joiner answers forwards the moment the
          commit broadcast lands — before its slice finishes copying.
        * replace (``replace=<dead id>``) — the standby takes over a
          dead member's host id at the SAME geometry: no slice moves,
          only an epoch bump re-points the dead id's address and
          re-plans in-flight forwards. The PR 11 journal replay
          back-fills whatever the survivors admitted on the dead id's
          behalf once their probes find the standby serving.

        The ship itself (``_ship_join_state``) runs BEFORE any routing
        changes: the standby adopts our CURRENT topology + epoch (so
        the subsequent prepare's FROM-epoch check passes), configures
        our limits generation, and imports the plan-cache seed — all
        while the pod keeps serving on the old membership."""
        started = time.time()
        old = self.router.topology
        if replace is None:
            new_id = old.hosts
            mode = "grow"
        else:
            new_id = int(replace)
            mode = "replace"
            if not (0 <= new_id < old.hosts):
                raise ValueError(
                    f"replace target {new_id} outside the "
                    f"{old.hosts}-host topology"
                )
            if new_id == self.host_id:
                raise ValueError("a host cannot replace itself")
        self.frontend.events.emit(
            "join_begin", mode=mode, joiner=new_id, address=address,
        )
        # the joiner must be dialable for the ship (and, in replace
        # mode, this overwrites the dead member's address)
        member_map = dict(self._peers)
        member_map[new_id] = str(address)
        self._peers = member_map
        self.lane.set_peers({
            h: a for h, a in member_map.items() if h != self.host_id
        })
        self.frontend.ensure_guards()
        try:
            seeded = self._ship_join_state(
                new_id, seed_plans, max_seed_entries
            )
            if mode == "grow":
                out = self.resize(
                    old.hosts + 1, peers={new_id: address}
                )
            else:
                out = self._drive_replace(new_id, member_map)
        except Exception:
            with self._lock:
                self.joins_aborted += 1
            self.frontend.events.emit(
                "join_end", mode=mode, joiner=new_id, ok=False,
            )
            raise
        seconds = time.time() - started
        with self._lock:
            if out.get("ok"):
                self.joins_completed += 1
            else:
                self.joins_aborted += 1
            self.join_seconds += seconds
            self.join_seed_entries += seeded
        self.frontend.events.emit(
            "join_end", mode=mode, joiner=new_id,
            ok=bool(out.get("ok")), seconds=round(seconds, 6),
            seeded=seeded,
        )
        return {
            **out, "mode": mode, "joiner": new_id,
            "join_seconds": round(seconds, 6), "seeded": seeded,
        }

    def _ship_join_state(
        self, host: int, seed_plans: bool, max_seed_entries: int
    ) -> int:
        """Ship the control-plane state a warm standby needs BEFORE the
        membership flip: adopt (topology + epoch + peers + its host
        id), limits (our applied generation, as identity wire), and —
        overlap, not critical path — the plan-cache seed. Returns the
        number of seed entries the joiner applied."""
        topo = self.router.topology
        resp = self.lane.admin_call(
            host,
            {
                "kind": "join_admin", "op": "adopt",
                "host_id": host,
                "hosts": topo.hosts,
                "shards_per_host": topo.shards_per_host,
                "tepoch": self.router.topology_epoch,
                "peers": {str(h): a for h, a in self._peers.items()},
                "from": self.host_id,
            },
            timeout=self.migrate_timeout_s,
        )
        if not resp.get("ok"):
            raise ValueError(
                f"joiner {host} refused adopt: {resp.get('error')}"
            )
        from ..tpu.plan_cache import _limit_identity_to_wire

        resp = self.lane.admin_call(
            host,
            {
                "kind": "join_admin", "op": "limits",
                "limits": [
                    _limit_identity_to_wire(lim)
                    for lim in self.frontend._last_limits
                ],
                "global_namespaces": sorted(self.frontend._global_ns),
                "from": self.host_id,
            },
            timeout=self.migrate_timeout_s,
        )
        if not resp.get("ok"):
            raise ValueError(
                f"joiner {host} refused limits: {resp.get('error')}"
            )
        if not seed_plans:
            return 0
        seed = self.frontend.plan_seed_export(
            max_entries=max_seed_entries
        )
        if not seed.get("entries"):
            return 0
        try:
            resp = self.lane.admin_call(
                host, {"kind": "plan_seed", **seed},
                timeout=self.migrate_timeout_s,
            )
        except Exception as exc:
            # the seed is an optimization, never a join blocker: a
            # joiner without it just compiles its plans on first miss
            log.warning(f"plan seed ship to joiner {host} failed: {exc}")
            return 0
        return int(resp.get("seeded", 0) or 0)

    def _drive_replace(self, new_id: int, member_map) -> dict:
        """Drive a same-geometry transition: the topology does not
        change shape, only the member map (a dead host id now answers
        at the standby's address) — so ``resize()``'s hosts==old noop
        shortcut cannot express it. Zero slices move; the epoch bump is
        what re-plans in-flight forwards stamped for the dead member
        and re-arms every member's guards at the new address."""
        old = self.router.topology
        with self._lock:
            if self.active or self._proposing:
                raise ValueError("a pod resize is already in flight")
            self._proposing = True
            transition = _Transition(
                old, old, member_map,
                tepoch_from=self.router.topology_epoch,
                tepoch_to=self.router.topology_epoch + 1,
                initiator=self.host_id,
            )
        try:
            return self._drive(
                transition, range(old.hosts), member_map
            )
        finally:
            with self._lock:
                self._proposing = False

    # -- member-side protocol handlers (lane loop — keep them fast) -----------

    def handle_admin(self, payload: dict) -> dict:
        op = payload.get("op")
        if op == "prepare":
            return self._handle_prepare(payload)
        if op == "commit":
            return self._handle_commit(payload)
        if op == "status":
            return self._handle_status(payload)
        if op == "abort":
            return self._handle_abort(payload)
        if op == "complete":
            return self._handle_complete(payload)
        return {"ok": False, "error": f"unknown resize op {op!r}"}

    def _plan_transition(self, payload: dict) -> _Transition:
        member_map = {
            int(h): str(a) for h, a in payload["peers"].items()
        }
        old = self.router.topology
        to_topo = PodTopology(
            hosts=int(payload["hosts"]), host_id=self.host_id,
            shards_per_host=int(payload["shards_per_host"]),
        )
        return _Transition(
            old, to_topo, member_map,
            tepoch_from=int(payload["tepoch_from"]),
            tepoch_to=int(payload["tepoch_to"]),
            initiator=int(payload.get("from", -1)),
        )

    def _handle_prepare(self, payload: dict) -> dict:
        with self._lock:
            if self.active:
                return {
                    "ok": False,
                    "error": "a pod resize is already in flight",
                }
            if int(payload["tepoch_from"]) != self.router.topology_epoch:
                return {
                    "ok": False,
                    "error": (
                        f"topology epoch mismatch: proposal from "
                        f"{payload['tepoch_from']}, host on "
                        f"{self.router.topology_epoch}"
                    ),
                }
            transition = self._plan_transition(payload)
            self._peers = transition.peers
        self.lane.set_peers({
            h: a for h, a in transition.peers.items()
            if h != self.host_id
        })
        self.frontend.ensure_guards()
        return {"ok": True, "tepoch": self.router.topology_epoch}

    def _handle_commit(self, payload: dict) -> dict:
        with self._lock:
            if self.active:
                t = self._transition
                if t is not None and t.tepoch_to == int(payload["tepoch_to"]):
                    return {"ok": True, "already": True}
                return {
                    "ok": False,
                    "error": "a different resize is already in flight",
                }
            if int(payload["tepoch_from"]) != self.router.topology_epoch:
                return {
                    "ok": False,
                    "error": "topology epoch moved between prepare and "
                             "commit",
                }
            transition = self._plan_transition(payload)
        self._commit(transition)
        return {"ok": True, "tepoch": self.router.topology_epoch}

    def _handle_status(self, payload: dict) -> dict:
        with self._lock:
            t = self._transition
            if t is None or t.tepoch_to != int(payload.get("tepoch_to", -1)):
                return {
                    "ok": True, "state": "none",
                    "tepoch": self.router.topology_epoch,
                }
            return {"ok": True, **t.snapshot()}

    def _handle_abort(self, payload: dict) -> dict:
        with self._lock:
            t = self._transition
            if t is None or t.tepoch_to != int(payload.get("tepoch_to", -1)):
                return {"ok": True, "state": "none"}
        # off-loop: the revert reverse-migrates ledgers (blocking RPCs)
        threading.Thread(
            target=self._abort,
            args=(t, payload.get("reason", "peer abort")),
            name=f"pod-resize-abort-{self.host_id}",
            daemon=True,
        ).start()
        return {"ok": True}

    def _handle_complete(self, payload: dict) -> dict:
        with self._lock:
            t = self._transition
            if t is None or t.tepoch_to != int(payload.get("tepoch_to", -1)):
                return {"ok": True, "state": "none"}
        self._complete(t)
        return {"ok": True}

    # -- joiner-side fast-join handlers (ISSUE 18) -----------------------------

    def handle_join(self, payload: dict):
        """The standby's side of the state ship (``kind:"join_admin"``
        lane RPC; armed by WarmStandby, never by attach_resize — the
        default construction stays byte-identical to PR 17). ``limits``
        returns a coroutine the lane dispatch awaits."""
        op = payload.get("op")
        if op == "adopt":
            return self._handle_join_adopt(payload)
        if op == "limits":
            return self._handle_join_limits(payload)
        if op == "status":
            return {
                "ok": True,
                "host": self.host_id,
                "tepoch": self.router.topology_epoch,
                "hosts": self.router.topology.hosts,
                "join_ttfd_seconds": self.join_ttfd_seconds,
            }
        return {"ok": False, "error": f"unknown join op {op!r}"}

    def _handle_join_adopt(self, payload: dict) -> dict:
        """Become host ``host_id`` of the shipped topology at its
        CURRENT epoch — the membership flip as a pure control-plane
        fact: no mesh reforms, no process restarts; the pre-formed
        host-local mesh and warm kernels keep serving. After this the
        initiator's prepare passes our FROM-epoch check and, in grow
        mode, every key still routes away from us (our id is outside
        the pre-grow geometry) until the commit lands."""
        new_id = int(payload["host_id"])
        tepoch = int(payload["tepoch"])
        peers = {
            int(h): str(a)
            for h, a in (payload.get("peers") or {}).items()
        }
        with self._lock:
            if self.active or self._proposing:
                return {
                    "ok": False,
                    "error": "a pod resize is already in flight",
                }
            if tepoch < self.router.topology_epoch:
                return {
                    "ok": False,
                    "error": (
                        f"adopt would move the topology epoch backward "
                        f"({self.router.topology_epoch} -> {tepoch})"
                    ),
                }
            self.host_id = new_id
            self.lane.host_id = new_id
            fe = self.frontend
            fe.events.host_id = new_id
            fe.hops.host_id = new_id
            fe.aggregator.host_id = new_id
            if peers:
                self._peers = peers
            topo = PodTopology(
                hosts=int(payload["hosts"]),
                host_id=new_id,
                shards_per_host=int(payload["shards_per_host"]),
            )
            self.router.retarget(topo, epoch=tepoch)
            self._join_adopted_at = time.time()
        self.lane.set_peers({
            h: a for h, a in peers.items() if h != new_id
        })
        self.frontend.ensure_guards()
        self.frontend.events.emit(
            "epoch_bump", tepoch=tepoch, hosts=int(payload["hosts"]),
            adopted=True, joiner=True,
        )
        return {"ok": True, "host": new_id, "tepoch": tepoch}

    def _handle_join_limits(self, payload: dict):
        """Configure the shipped limits generation (a coroutine — the
        lane loop awaits it; configure_with is async because the inner
        limiter may be). Limits arrive as identity wire dicts, the same
        portable form the plan-seed blobs carry."""
        from ..core import Limit

        limits = []
        for ident in payload.get("limits") or ():
            limits.append(Limit(
                ident["ns"], ident["max"], ident["seconds"],
                list(ident.get("conditions") or ()),
                list(ident.get("variables") or ()),
                name=ident.get("name"), id=ident.get("id"),
                policy=ident.get("policy") or "fixed_window",
            ))
        self.frontend._global_ns = {
            str(ns) for ns in payload.get("global_namespaces") or ()
        }

        async def _apply():
            await self.frontend.configure_with(limits)
            return {"ok": True, "limits": len(limits)}

        return _apply()

    def note_first_decision(self) -> None:
        """Stamp time-to-first-decision on the joiner: called from the
        forwarded-decision path after a join adopt. Self-disarming —
        one unlocked read once stamped."""
        if self._join_adopted_at is None:
            return
        with self._lock:
            adopted = self._join_adopted_at
            if adopted is None:
                return
            self._join_adopted_at = None
            self.join_ttfd_seconds = round(time.time() - adopted, 6)

    # -- the transition machinery ----------------------------------------------

    def _commit(self, transition: _Transition) -> None:
        """Flip routing to the new topology at the agreed epoch and
        start migrating. Runs on the lane loop (member) or the
        initiator's driver thread — fast: lock + retarget + thread
        spawn; the heavy lifting happens on the migration thread."""
        events = self.frontend.events
        with self._lock:
            self._transition = transition
            self._received = {}
            self._peers = transition.peers
            transition.pinned_from = self.router.pinned_map()
            events.emit(
                "resize_begin",
                from_hosts=transition.from_topology.hosts,
                to_hosts=transition.to_topology.hosts,
                tepoch=transition.tepoch_to,
                initiator=transition.initiator,
            )
            tepoch = self.router.retarget(
                transition.to_topology, epoch=transition.tepoch_to
            )
            transition.pinned_to = self.router.pinned_map()
            events.emit(
                "epoch_bump", tepoch=tepoch,
                hosts=transition.to_topology.hosts,
            )
            transition.state = "migrating"
            self._watchdog = threading.Timer(
                self.transition_timeout_s + 5.0,
                self._watchdog_fired, args=(transition,),
            )
            self._watchdog.daemon = True
            self._watchdog.start()
        threading.Thread(
            target=self._migrate_out, args=(transition,),
            name=f"pod-resize-migrate-{self.host_id}",
            daemon=True,
        ).start()

    def _watchdog_fired(self, transition: _Transition) -> None:
        """A transition the initiator never resolved (it may have died
        mid-protocol): self-abort so the host is not stuck in-flight
        forever. A completed-or-aborted transition is a no-op."""
        with self._lock:
            if self._transition is not transition:
                return
            if transition.state in ("aborted", "complete"):
                return
            if transition.state == "done":
                # everyone may be done and only the complete broadcast
                # was lost: completing is the safe self-resolution
                pass
        if transition.state == "done":
            self._complete(transition)
        else:
            self._abort(transition, "transition watchdog expired")

    def _complete(self, transition: _Transition) -> None:
        with self._lock:
            if self._transition is not transition:
                return
            if transition.state not in ("done", "migrating", "armed"):
                return
            transition.state = "complete"
            transition.finished = time.time()
            self._received = {}
            self.completed += 1
            self.resize_seconds += (
                transition.finished - transition.started
            )
            if self._watchdog is not None:
                self._watchdog.cancel()
                self._watchdog = None

    # -- outbound migration ------------------------------------------------------

    def _values_for(self, namespaces) -> Dict[tuple, Tuple[object, int]]:
        """key -> (counter, absolute value) for every live counter in
        the given namespaces — the migration source's view. Values come
        off the limiter's get_counters surface (remaining is unclamped
        there, so value = max - remaining is exact)."""
        import asyncio as _asyncio
        import inspect as _inspect

        out: Dict[tuple, Tuple[object, int]] = {}
        for ns in namespaces:
            counters = self.frontend._limiter.get_counters(ns)
            if _inspect.isawaitable(counters):
                counters = _asyncio.run(counters)
            for counter in counters:
                value = int(counter.max_value) - int(counter.remaining)
                if value <= 0:
                    continue
                out[counter_key(counter)] = (counter, value)
        return out

    def _migrating_namespaces(self) -> List[str]:
        namespaces = sorted({
            str(limit.namespace)
            for limit in self.frontend._last_limits
        })
        psum = self.frontend.psum_lane
        if psum is not None:
            # psum-served namespaces decide read-as-sum locally on
            # every host — there is no slice to move
            namespaces = [
                ns for ns in namespaces if ns not in psum.namespaces
            ]
        return namespaces

    def _migrate_out(self, transition: _Transition) -> None:
        """The migration thread: stream every slice this host owned
        under FROM but not under TO to its new owner, convergence-swept
        and released. Failure marks the transition failed; the
        initiator's poll turns that into a pod-wide abort."""
        from .peering import _counter_to_wire

        try:
            pipeline = self.frontend.pipeline
            if pipeline is not None:
                # Lease recall + C-mirror re-stamp (ISSUE 15): the plan
                # cache's epoch bump pushes outstanding leased balances
                # onto the return ring (PR 6) and the pod re-attach
                # re-derives every plan's owner stamp under the new
                # topology.
                try:
                    pipeline.attach_pod(self.frontend)
                except Exception:
                    pass
                try:
                    pipeline.invalidate()
                except Exception:
                    pass
            me = self.host_id
            namespaces = self._migrating_namespaces()
            values = self._values_for(namespaces)
            # group moving keys into slices: slice id = the key's
            # global shard under the NEW topology
            slices: Dict[Tuple[int, int], List[tuple]] = {}
            for key, (counter, _value) in values.items():
                ns = str(counter.namespace)
                owner_from = _owner_of(
                    key, ns, transition.from_topology,
                    transition.pinned_from,
                )
                owner_to = _owner_of(
                    key, ns, transition.to_topology, transition.pinned_to,
                )
                if owner_from != me or owner_to == me:
                    continue
                slice_id = transition.to_topology.owner_shard(key)
                slices.setdefault((owner_to, slice_id), []).append(key)
            storage = self._storage()
            drop = getattr(storage, "drop_counter", None)
            for (owner, slice_id), keys in sorted(slices.items()):
                if transition.aborting:
                    return
                self.frontend.events.emit(
                    "migrate_begin", slice=slice_id, owner=owner,
                    counters=len(keys),
                )
                if self.slice_pause_s > 0:
                    # chaos hook: a deterministic mid-migration window
                    time.sleep(self.slice_pause_s)
                ns_set = sorted({str(values[k][0].namespace) for k in keys})
                sent: Dict[tuple, int] = {}
                moved = 0
                for _sweep in range(self.MAX_SWEEPS):
                    if transition.aborting:
                        return
                    fresh = self._values_for(ns_set)
                    rows = []
                    for key in keys:
                        entry = fresh.get(key)
                        if entry is None:
                            continue
                        counter, value = entry
                        if value > sent.get(key, 0):
                            rows.append(_counter_to_wire(counter, value))
                            sent[key] = value
                    if not rows and _sweep > 0:
                        break  # converged: nothing accrued during copy
                    if rows:
                        moved += len(rows)
                        self._send_slice(
                            transition, owner, slice_id, rows, final=False
                        )
                # the final marker releases the slice at the receiver's
                # ledger; only then do we drop our cells
                self._send_slice(transition, owner, slice_id, [], final=True)
                released = 0
                for key in keys:
                    entry = values.get(key)
                    if entry is None:
                        continue
                    if drop is not None and drop(entry[0]):
                        released += 1
                with self._lock:
                    transition.moved_slices += 1
                    transition.moved_counters += len(keys)
                    self.slices_moved += 1
                    self.moved_deltas += moved
                    self.released_counters += released
                self.frontend.events.emit(
                    "migrate_end", slice=slice_id, owner=owner,
                    counters=len(keys), released=released,
                )
            with self._lock:
                if transition.state == "migrating":
                    transition.state = "done"
            self.frontend.events.emit(
                "resize_end",
                tepoch=transition.tepoch_to,
                hosts=transition.to_topology.hosts,
                moved_slices=transition.moved_slices,
                moved_counters=transition.moved_counters,
            )
        except Exception as exc:
            log.warning(f"pod resize: migration failed: {exc}")
            with self._lock:
                if transition.state == "migrating":
                    transition.state = "failed"
                    transition.error = f"{exc}"[:300]

    def _send_slice(
        self, transition: _Transition, owner: int, slice_id: int,
        rows: List[dict], final: bool,
    ) -> None:
        """Ship one slice batch (chunked, retried; idempotent — the
        receiver diffs against its ledger). Raises when the owner stays
        unreachable or rejects the transition epoch."""
        chunks = [
            rows[i:i + self.CHUNK] for i in range(0, len(rows), self.CHUNK)
        ] or [[]]
        for idx, chunk in enumerate(chunks):
            payload = {
                "kind": "migrate",
                "tepoch": transition.tepoch_to,
                "slice": int(slice_id),
                "from": self.host_id,
                "rows": chunk,
                "final": bool(final and idx == len(chunks) - 1),
            }
            last: Optional[Exception] = None
            for attempt in range(self.MIGRATE_RETRIES):
                if transition.aborting:
                    raise RuntimeError("transition aborting")
                try:
                    resp = self.lane.admin_call(
                        owner, payload, timeout=self.migrate_timeout_s
                    )
                except Exception as exc:
                    last = exc
                    time.sleep(0.1 * (attempt + 1))
                    continue
                if resp.get("stale_epoch"):
                    # the receiver may simply not have committed yet
                    # (our migration thread races the initiator's
                    # commit broadcast): back off and retry before
                    # declaring the epoch disagreement terminal
                    last = RuntimeError(
                        f"owner {owner} rejected migrate for epoch "
                        f"{transition.tepoch_to} (on {resp.get('tepoch')})"
                    )
                    time.sleep(0.1 * (attempt + 1))
                    continue
                if not resp.get("ok"):
                    raise RuntimeError(
                        f"owner {owner} refused migrate: "
                        f"{resp.get('error')}"
                    )
                last = None
                break
            if last is not None:
                raise RuntimeError(
                    f"owner {owner} unreachable for slice {slice_id}: "
                    f"{last}"
                )

    # -- inbound migration (the receiving owner) --------------------------------

    def handle_migrate(self, payload: dict) -> dict:
        """Apply one migrated slice batch (lane executor thread). Rows
        carry ABSOLUTE values; the per-transition ledger turns them
        into apply-once diffs, so retries and re-driven transitions
        never double-apply."""
        from .peering import _counter_from_wire

        slice_id = int(payload.get("slice", -1))
        items = []
        with self._lock:
            ledger = self._received.setdefault(
                slice_id, {"rows": {}, "final": False}
            )
            for blob in payload.get("rows", ()):
                counter, value = _counter_from_wire(blob)
                value = max(int(value), 0)
                key = counter_key(counter)
                prev = ledger["rows"].get(key)
                received = prev[1] if prev is not None else 0
                diff = value - received
                if diff > 0:
                    items.append((counter, diff))
                    ledger["rows"][key] = (counter, value)
                elif prev is None:
                    ledger["rows"][key] = (counter, value)
                # value < received: the window rolled at the source —
                # keep the higher mark; nothing to apply
            if payload.get("final"):
                ledger["final"] = True
        if items:
            self._storage().apply_deltas(items)
            with self._lock:
                self.moved_deltas += len(items)
        return {"ok": True, "applied": len(items)}

    # -- abort: revert to the FROM topology with nothing lost -------------------

    def _broadcast_abort(self, transition: _Transition, reason: str) -> None:
        members = [
            h for h in transition.peers if h != self.host_id
        ]
        for host in members:
            try:
                self.lane.admin_call(
                    host,
                    {
                        "kind": "resize_admin", "op": "abort",
                        "tepoch_to": transition.tepoch_to,
                        "reason": reason, "from": self.host_id,
                    },
                    timeout=self.migrate_timeout_s,
                )
            except Exception:
                pass  # a dead member aborts via its own watchdog
        self._abort(transition, reason)

    def _abort(self, transition: _Transition, reason: str) -> None:
        """Revert this host to the FROM topology (at a new agreed
        epoch), push received slices back to their reverted owners and
        redistribute journals accrued against removed members. Safe to
        race: only the first caller flips the state."""
        from .peering import _counter_to_wire

        with self._lock:
            if self._transition is not transition:
                return
            if transition.state in ("aborted", "complete"):
                return
            transition.aborting = True
            transition.state = "aborted"
            transition.error = transition.error or reason
            transition.finished = time.time()
            received, self._received = self._received, {}
            self.aborted += 1
            self.resize_seconds += (
                transition.finished - transition.started
            )
            if self._watchdog is not None:
                self._watchdog.cancel()
                self._watchdog = None
            # every member reverts to the SAME post-abort epoch:
            # tepoch_to + 1 (epochs only move forward)
            self.router.retarget(
                transition.from_topology, epoch=transition.tepoch_to + 1
            )
        self.frontend.events.emit(
            "resize_abort", tepoch=transition.tepoch_to + 1,
            reason=str(reason)[:200],
        )
        pipeline = self.frontend.pipeline
        if pipeline is not None:
            try:
                pipeline.attach_pod(self.frontend)
                pipeline.invalidate()
            except Exception:
                pass
        self.lane.set_peers({
            h: a for h, a in self._peers.items()
            if h != self.host_id and h < transition.from_topology.hosts
        })
        # 1) push back what we received (+ what we admitted meanwhile):
        # full values for finalized slices (the source released), the
        # value-minus-received delta for partial ones (the source kept
        # its copy). Ships over apply_deltas — deliberately NOT epoch
        # gated, so it lands regardless of commit/revert skew.
        storage = self._storage()
        drop = getattr(storage, "drop_counter", None)
        values: Dict[tuple, Tuple[object, int]] = {}
        try:
            values = self._values_for(self._migrating_namespaces())
        except Exception as exc:
            log.warning(f"pod resize abort: value sweep failed: {exc}")
        send_back: Dict[int, List[dict]] = {}
        to_drop = []
        with self._lock:
            pinned = self.router.pinned_map()
            for slice_id, ledger in received.items():
                for key, (counter, received_val) in ledger["rows"].items():
                    ns = str(counter.namespace)
                    owner = _owner_of(
                        key, ns, transition.from_topology, pinned
                    )
                    if owner == self.host_id:
                        continue  # we own it under FROM too: keep it
                    entry = values.get(key)
                    value_now = entry[1] if entry is not None else 0
                    delta = (
                        value_now if ledger["final"]
                        else value_now - received_val
                    )
                    if delta > 0:
                        send_back.setdefault(owner, []).append(
                            _counter_to_wire(counter, delta)
                        )
                    to_drop.append(counter)
        for owner, deltas in send_back.items():
            try:
                for start in range(0, len(deltas), self.CHUNK):
                    self.lane.replay_deltas(
                        owner, deltas[start:start + self.CHUNK],
                        timeout=self.migrate_timeout_s,
                    )
            except Exception as exc:
                log.warning(
                    f"pod resize abort: push-back to host {owner} failed "
                    f"({exc}); its keys stay here until the next "
                    "transition"
                )
                # do NOT drop what we could not push back
                to_drop = [
                    c for c in to_drop
                    if _owner_of(
                        counter_key(c), str(c.namespace),
                        transition.from_topology, pinned,
                    ) != owner
                ]
        if drop is not None:
            for counter in to_drop:
                drop(counter)
        # 2) journals accrued against members the revert removed (the
        # SIGKILLed new host of the drill): their keys' CURRENT owners
        # under FROM must absorb them — the normal probe-driven replay
        # would wait forever for a host that is no longer a member.
        # Swept twice: a decision already inside the degraded path when
        # the revert landed may journal between the sweeps.
        self.sweep_orphan_journals()
        time.sleep(0.05)
        self.sweep_orphan_journals()
        log.warning(
            f"pod resize aborted (reverted to "
            f"{transition.from_topology.hosts} hosts): {reason}"
        )

    def sweep_orphan_journals(self) -> int:
        """Drain journals accrued against hosts that are NOT members of
        the CURRENT topology into the keys' current owners (local
        apply or apply_deltas over the lane). Returns the number of
        counter deltas redistributed. Runs during an abort and is safe
        to call any time a transition removed members — the normal
        probe-driven replay only serves owners that are still members."""
        from .peering import _counter_to_wire

        guards = getattr(self.frontend, "_guards", {})
        with self._lock:
            topology = self.router.topology
            pinned = self.router.pinned_map()
        moved = 0
        for owner, guard in list(guards.items()):
            if owner < topology.hosts:
                continue  # still a member: normal recovery replays it
            if guard.store.journal_size() == 0:
                continue
            items = guard.store.drain()
            local_items = []
            remote: Dict[int, List[Tuple]] = {}
            for counter, delta in items:
                key = counter_key(counter)
                ns = str(counter.namespace)
                target = _owner_of(key, ns, topology, pinned)
                if target == self.host_id:
                    local_items.append((counter, delta))
                else:
                    remote.setdefault(target, []).append(
                        (counter, delta)
                    )
            # a delta is only GONE once some owner acknowledged it: any
            # slice of the drain that fails to land is re-journaled (the
            # reconcile_into un-acked-tail contract, out-of-band), and
            # the oracle's window state survives with it so the next
            # degraded decision stays consistent with the journal.
            failed: List[Tuple] = []
            if local_items:
                try:
                    self._storage().apply_deltas(local_items)
                    moved += len(local_items)
                except Exception as exc:
                    failed.extend(local_items)
                    log.warning(
                        "pod resize: local journal redistribute "
                        f"failed: {exc}"
                    )
            for target, pairs in remote.items():
                deltas = [
                    _counter_to_wire(counter, delta)
                    for counter, delta in pairs
                ]
                acked = 0
                try:
                    for start in range(0, len(deltas), self.CHUNK):
                        self.lane.replay_deltas(
                            target, deltas[start:start + self.CHUNK],
                            timeout=self.migrate_timeout_s,
                        )
                        acked = min(start + self.CHUNK, len(pairs))
                    moved += len(pairs)
                except Exception as exc:
                    moved += acked
                    failed.extend(pairs[acked:])
                    log.warning(
                        "pod resize: journal redistribute to host "
                        f"{target} failed after {acked} deltas: {exc}"
                    )
            if failed:
                guard.store.rejournal(failed)
            else:
                guard.store.reset_oracle()
        return moved

    # -- origin-side adoption ----------------------------------------------------

    def adopt_remote(self, resp: dict) -> None:
        """A stale_epoch rejection carried a NEWER topology than ours:
        adopt it (geometry + peers) so the re-plan routes correctly. A
        host that missed the commit broadcast catches up here; its own
        outbound migration is re-driven by the initiator's poll. Older
        or equal epochs are ignored — epochs only move forward."""
        tepoch = int(resp.get("tepoch", -1))
        topo = resp.get("topology") or {}
        if not topo:
            return
        with self._lock:
            # the epoch comparison must sit INSIDE the lock: an abort
            # racing this adoption bumps the epoch past tepoch, and a
            # stale outside-the-lock verdict would retarget BACKWARD
            # onto the aborted geometry
            if tepoch <= self.router.topology_epoch:
                return
            if self.active:
                return  # mid-transition: the protocol owns the epoch
            peers = {
                int(h): str(a)
                for h, a in (resp.get("peers") or {}).items()
            }
            if peers:
                self._peers = peers
            new_topo = PodTopology(
                hosts=int(topo["hosts"]),
                host_id=self.host_id,
                shards_per_host=int(topo["shards_per_host"]),
            )
            self.router.retarget(new_topo, epoch=tepoch)
        if peers:
            self.lane.set_peers({
                h: a for h, a in peers.items() if h != self.host_id
            })
            self.frontend.ensure_guards()
        self.frontend.events.emit(
            "epoch_bump", tepoch=tepoch, hosts=int(topo["hosts"]),
            adopted=True,
        )

    # -- telemetry ---------------------------------------------------------------

    def status(self) -> dict:
        """The ``GET /debug/pod/resize`` payload (and the ``pod_resize``
        /debug/stats section body)."""
        with self._lock:
            t = self._transition
            received = {
                str(slice_id): {
                    "counters": len(ledger["rows"]),
                    "final": ledger["final"],
                }
                for slice_id, ledger in self._received.items()
            }
        return {
            "host": self.host_id,
            "topology_epoch": self.router.topology_epoch,
            "hosts": self.router.topology.hosts,
            "active": self.active,
            "transition": t.snapshot() if t is not None else None,
            "received_slices": received,
            "completed": self.completed,
            "aborted": self.aborted,
            "peers": {str(h): a for h, a in self._peers.items()},
        }

    def stats(self) -> dict:
        """The ``pod_resize_*`` family feed (library_stats keys; the
        lane adds pod_resize_stale_rejects, the frontend
        pod_resize_replans)."""
        return {
            "pod_resize_epoch": self.router.topology_epoch,
            "pod_resize_active": 1 if self.active else 0,
            "pod_resize_completed": self.completed,
            "pod_resize_aborted": self.aborted,
            "pod_resize_slices_moved": self.slices_moved,
            "pod_resize_moved_deltas": self.moved_deltas,
            "pod_resize_released_counters": self.released_counters,
            "pod_resize_seconds": round(self.resize_seconds, 6),
            "join_completed": self.joins_completed,
            "join_aborted": self.joins_aborted,
            "join_seconds": round(self.join_seconds, 6),
            "join_seed_entries": self.join_seed_entries,
            "join_ttfd_seconds": self.join_ttfd_seconds,
        }
