"""Envoy RLS v3 + Kuadrant RLS v1 gRPC services.

Mirrors /root/reference/limitador-server/src/envoy_rls/server.rs and
kuadrant_service.rs over grpc.aio with generic method handlers (the
environment ships protoc without the gRPC python plugin; the service
surface is just method paths + message (de)serializers).

Behavioral parity points:
- empty domain -> overall_code UNKNOWN (server.rs:106-116);
- descriptors bind as the ``descriptors`` list-of-maps CEL variable
  (server.rs:121-139);
- hits_addend 0 -> 1 (the proto default is 0 but the spec default is 1,
  server.rs:129-135);
- storage errors -> gRPC UNAVAILABLE so Envoy's failure_mode_deny policy
  decides fail-open/closed (server.rs:160-172);
- optional draft-03 ratelimit response headers (server.rs:179-207);
- Kuadrant split: CheckRateLimit is read-only, Report only updates
  (kuadrant_service.rs:27-184).
"""

from __future__ import annotations

import contextlib
from typing import Optional

import grpc

_NULLCONTEXT = contextlib.nullcontext()

from ..core.cel import Context
from ..core.limiter import AsyncRateLimiter, CheckResult
from ..observability.metrics import PrometheusMetrics
from ..observability.metrics_layer import (
    installed as _metrics_layer_installed,
    metrics_span,
)
from ..observability.tracing import should_rate_limit_span, tracing_enabled
from ..storage.base import StorageError
from .proto import rls_pb2

__all__ = [
    "RATE_LIMIT_HEADERS_NONE",
    "RATE_LIMIT_HEADERS_DRAFT03",
    "RlsServingShard",
    "make_rls_handlers",
    "serve_rls",
]

RATE_LIMIT_HEADERS_NONE = "NONE"
RATE_LIMIT_HEADERS_DRAFT03 = "DRAFT_VERSION_03"

_ENVOY_SERVICE = "envoy.service.ratelimit.v3.RateLimitService"
_KUADRANT_SERVICE = "kuadrant.service.ratelimit.v1.RateLimitService"


def _context_from_request(req) -> Context:
    values = []
    for descriptor in req.descriptors:
        values.append({e.key: e.value for e in descriptor.entries})
    ctx = Context()
    ctx.list_binding("descriptors", values)
    return ctx


def _hits_addend(req) -> int:
    return req.hits_addend if req.hits_addend != 0 else 1


# Headerless responses carry only the overall code: pre-built singletons
# (never mutated; SerializeToString on a settled message is safe from any
# thread) replace a per-request protobuf construction on the hot path.
_PLAIN_RESPONSES = {
    code: rls_pb2.RateLimitResponse(overall_code=code)
    for code in (
        rls_pb2.RateLimitResponse.OK,
        rls_pb2.RateLimitResponse.OVER_LIMIT,
        rls_pb2.RateLimitResponse.UNKNOWN,
    )
}
_UNKNOWN_RESPONSE = _PLAIN_RESPONSES[rls_pb2.RateLimitResponse.UNKNOWN]


def _response(code, result: Optional[CheckResult], with_headers: bool):
    if not with_headers or result is None:
        return _PLAIN_RESPONSES[code]
    resp = rls_pb2.RateLimitResponse(overall_code=code)
    for key, value in result.response_header().items():
        resp.response_headers_to_add.add(key=key, value=value)
    return resp


class RlsService:
    """Shared implementation behind both gRPC services."""

    def __init__(
        self,
        limiter,
        metrics: Optional[PrometheusMetrics] = None,
        rate_limit_headers: str = RATE_LIMIT_HEADERS_NONE,
        admission=None,
    ):
        self.limiter = limiter
        self.metrics = metrics
        # Admission controller (admission/controller.py): deadline/
        # overload shedding before a request occupies a batch slot.
        # None = pre-admission-plane behavior.
        self.admission = admission
        self.rate_limit_headers = rate_limit_headers
        # Async limiters: the batched facades and the pod frontend
        # (server/peering.py), whose forwarded decisions await the
        # peer lane and so must be awaited here too.
        self._is_async = isinstance(limiter, AsyncRateLimiter) or getattr(
            limiter, "is_async_limiter", False
        )
        # Batched storages time their own device round trips (the busy-time
        # semantics of the reference's MetricsLayer, metrics.rs:100-211);
        # wrapping here would add queue wait on top.
        from ..observability.metrics import storage_self_timed

        self._self_timed = storage_self_timed(limiter)

    def _timed(self, batched: bool = False):
        """datastore_latency fallback around storage calls. With a
        MetricsLayer installed (the server default), the reference's
        aggregates own the histogram — only the should_rate_limit and
        flush roots feed it (main.rs:908-917; the Kuadrant/HTTP handlers
        are instrumented with non-aggregate names there too) — so this
        wrapper stands down. Without one (bare-library embedding), the
        wall-clock sample is kept. ``batched`` marks operations the
        batched storages time themselves (queue excluded, into
        datastore_latency when no layer is installed) — those skip the
        wrapper too."""
        if _metrics_layer_installed() is not None:
            return _NULLCONTEXT
        if self.metrics is not None and not (batched and self._self_timed):
            return self.metrics.time_datastore()
        return _NULLCONTEXT

    async def _check_and_update(self, namespace, ctx, delta, load):
        with self._timed(batched=True):
            if self._is_async:
                return await self.limiter.check_rate_limited_and_update(
                    namespace, ctx, delta, load
                )
            return self.limiter.check_rate_limited_and_update(
                namespace, ctx, delta, load
            )

    async def _is_rate_limited(self, namespace, ctx, delta):
        with self._timed():
            if self._is_async:
                return await self.limiter.is_rate_limited(
                    namespace, ctx, delta
                )
            return self.limiter.is_rate_limited(namespace, ctx, delta)

    async def _update_counters(self, namespace, ctx, delta):
        with self._timed(batched=True):
            if self._is_async:
                await self.limiter.update_counters(namespace, ctx, delta)
            else:
                self.limiter.update_counters(namespace, ctx, delta)

    async def _admit(self, request, context, namespace):
        """Admission-plane gate before the storage decision. Returns a
        ticket (or None) to release when the decision resolves, or a
        ready RateLimitResponse when the request was shed with
        OVER_LIMIT semantics; UNAVAILABLE sheds abort the RPC (Envoy's
        failure_mode_deny then decides fail-open/closed, exactly like a
        storage error)."""
        from ..admission.controller import AdmissionShed

        values = None
        if request.descriptors:
            values = {
                e.key: e.value for e in request.descriptors[0].entries
            }
        time_remaining = getattr(context, "time_remaining", None)
        deadline = time_remaining() if callable(time_remaining) else None
        try:
            return self.admission.admit(namespace, values, deadline)
        except AdmissionShed as shed:
            if shed.overlimit:
                return _PLAIN_RESPONSES[
                    rls_pb2.RateLimitResponse.OVER_LIMIT
                ]
            await context.abort(
                grpc.StatusCode.UNAVAILABLE, f"Service unavailable: {shed}"
            )

    # -- Envoy ShouldRateLimit (THE hot path) -----------------------------

    async def should_rate_limit(self, request, context):
        namespace = request.domain
        if not namespace:
            return _UNKNOWN_RESPONSE
        ctx = _context_from_request(request)
        hits_addend = _hits_addend(request)
        with_headers = self.rate_limit_headers != RATE_LIMIT_HEADERS_NONE
        ticket = None
        if self.admission is not None:
            shed = await self._admit(request, context, namespace)
            if isinstance(shed, rls_pb2.RateLimitResponse):
                return shed
            ticket = shed
        # W3C trace-context from gRPC metadata parents the span
        # (envoy_rls/server.rs:100-104); only materialized when an
        # exporter is actually installed.
        try:
            carrier = None
            if tracing_enabled():
                carrier = dict(context.invocation_metadata() or ())
            with should_rate_limit_span(
                namespace, hits_addend, carrier
            ) as record:
                try:
                    result = await self._check_and_update(
                        namespace, ctx, hits_addend, with_headers
                    )
                except StorageError as exc:
                    await context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        f"Service unavailable: {exc}",
                    )
                record(result.limited, result.limit_name)
        finally:
            if ticket is not None:
                ticket.release()
        if self.metrics:
            # evaluate the custom label map once per request
            extra = self.metrics.custom_labels(ctx)
        if result.limited:
            if self.metrics:
                self.metrics.incr_limited_calls(
                    namespace, result.limit_name, labels=extra
                )
            code = rls_pb2.RateLimitResponse.OVER_LIMIT
        else:
            if self.metrics:
                self.metrics.incr_authorized_calls(namespace, labels=extra)
                self.metrics.incr_authorized_hits(
                    namespace, hits_addend, labels=extra
                )
            code = rls_pb2.RateLimitResponse.OK
        return _response(code, result, with_headers)

    # -- Kuadrant check/report split --------------------------------------

    async def check_rate_limit(self, request, context):
        namespace = request.domain
        if not namespace:
            return _UNKNOWN_RESPONSE
        ctx = _context_from_request(request)
        try:
            # The reference checks with delta 1 regardless of hits_addend
            # (kuadrant_service.rs check path); the addend applies on Report.
            result = await self._is_rate_limited(namespace, ctx, 1)
        except StorageError as exc:
            await context.abort(
                grpc.StatusCode.UNAVAILABLE, f"Service unavailable: {exc}"
            )
        if result.limited:
            if self.metrics:
                self.metrics.incr_limited_calls(
                    namespace, result.limit_name, ctx=ctx
                )
            code = rls_pb2.RateLimitResponse.OVER_LIMIT
        else:
            if self.metrics:
                self.metrics.incr_authorized_calls(namespace, ctx=ctx)
            code = rls_pb2.RateLimitResponse.OK
        with_headers = self.rate_limit_headers != RATE_LIMIT_HEADERS_NONE
        return _response(code, result, with_headers)

    async def report(self, request, context):
        namespace = request.domain
        if not namespace:
            return _UNKNOWN_RESPONSE
        ctx = _context_from_request(request)
        hits_addend = _hits_addend(request)
        try:
            await self._update_counters(namespace, ctx, hits_addend)
        except StorageError as exc:
            await context.abort(
                grpc.StatusCode.UNAVAILABLE, f"Service unavailable: {exc}"
            )
        if self.metrics:
            # Report counts hits only (kuadrant_service.rs report path);
            # authorized_calls is counted by CheckRateLimit.
            self.metrics.incr_authorized_hits(namespace, hits_addend, ctx=ctx)
        return _PLAIN_RESPONSES[rls_pb2.RateLimitResponse.OK]


def make_rls_handlers(service: RlsService):
    """Generic handlers for both services (no grpc plugin codegen needed)."""
    req_des = rls_pb2.RateLimitRequest.FromString
    resp_ser = lambda m: m.SerializeToString()

    envoy = grpc.method_handlers_generic_handler(
        _ENVOY_SERVICE,
        {
            "ShouldRateLimit": grpc.unary_unary_rpc_method_handler(
                service.should_rate_limit,
                request_deserializer=req_des,
                response_serializer=resp_ser,
            )
        },
    )
    kuadrant = grpc.method_handlers_generic_handler(
        _KUADRANT_SERVICE,
        {
            "CheckRateLimit": grpc.unary_unary_rpc_method_handler(
                service.check_rate_limit,
                request_deserializer=req_des,
                response_serializer=resp_ser,
            ),
            "Report": grpc.unary_unary_rpc_method_handler(
                service.report,
                request_deserializer=req_des,
                response_serializer=resp_ser,
            ),
        },
    )
    return [envoy, kuadrant]


def _native_should_rate_limit(native_pipeline, admission=None):
    """The raw-bytes ShouldRateLimit coroutine shared by the aio server
    handler and the sync serving shards' bridge: admission gate, then
    ``submit`` on the calling loop's pipeline shard."""
    from ..admission.controller import AdmissionShed

    async def handler(blob: bytes, context) -> bytes:
        ticket = None
        if admission is not None:
            time_remaining = getattr(context, "time_remaining", None)
            deadline = (
                time_remaining() if callable(time_remaining) else None
            )
            try:
                ticket = admission.admit(None, None, deadline)
            except AdmissionShed as shed:
                if shed.overlimit:
                    return native_pipeline.OVER_BLOB
                await context.abort(
                    grpc.StatusCode.UNAVAILABLE,
                    f"Service unavailable: {shed}",
                )
        try:
            # MetricsLayer aggregate for the native path: the one storage
            # wait (parse -> device -> response blob) is the request's
            # datastore time. metrics_span (not the OTel wrapper) keeps
            # this a pair of module-global checks when no layer is
            # installed — nothing else rides the raw-bytes hot path.
            with metrics_span("should_rate_limit"):
                with metrics_span("datastore"):
                    return await native_pipeline.submit(blob)
        except StorageError as exc:
            await context.abort(
                grpc.StatusCode.UNAVAILABLE, f"Service unavailable: {exc}"
            )
        finally:
            if ticket is not None:
                ticket.release()

    return handler


def make_native_should_rate_limit_handler(native_pipeline, admission=None):
    """ShouldRateLimit over RAW request bytes: identity (de)serializers keep
    Python protobuf off the hot path entirely — the native pipeline parses
    the wire bytes in C++ and answers with prebuilt response blobs.

    With an admission controller, deadline/overload shedding happens
    before the blob enters the pipeline — priority resolves without
    parsing (the default class), since descriptor entries only
    materialize in C++ past this point."""
    return grpc.method_handlers_generic_handler(
        _ENVOY_SERVICE,
        {
            "ShouldRateLimit": grpc.unary_unary_rpc_method_handler(
                _native_should_rate_limit(native_pipeline, admission),
                request_deserializer=None,   # raw bytes in
                response_serializer=None,    # raw bytes out
            )
        },
    )


def make_native_method_handlers(service: "RlsService"):
    """Cold-path method table for the native ingress: the Kuadrant
    check/report split (and Envoy ShouldRateLimit as a fallback entry for
    completeness) served through the same RlsService the Python gRPC
    server uses, adapted to raw request/response bytes. Makes the C++
    ingress a complete single-port server (kuadrant_service.rs parity);
    the hot Envoy path never reaches these — it rides the columnar
    engine in C++/numpy."""
    from ..native.ingress import GrpcHandlerError

    class _ShimContext:
        """The slice of grpc.ServicerContext the handlers use."""

        @staticmethod
        async def abort(code, details=""):
            raise GrpcHandlerError(
                code.value[0], str(details).encode()[:100]
            )

        @staticmethod
        def invocation_metadata():
            return ()

    def adapt(method):
        async def handler(blob: bytes) -> bytes:
            request = rls_pb2.RateLimitRequest.FromString(blob)
            response = await method(request, _ShimContext())
            return response.SerializeToString()

        return handler

    # No ShouldRateLimit entry: the ingress nulls the target path in C++
    # and routes it to the columnar engine — an entry here could never
    # fire and would mislead about which code serves the hot path.
    return {
        f"/{_KUADRANT_SERVICE}/CheckRateLimit": adapt(service.check_rate_limit),
        f"/{_KUADRANT_SERVICE}/Report": adapt(service.report),
    }


async def serve_rls(
    limiter,
    address: str = "0.0.0.0:8081",
    metrics: Optional[PrometheusMetrics] = None,
    rate_limit_headers: str = RATE_LIMIT_HEADERS_NONE,
    native_pipeline=None,
    admission=None,
) -> grpc.aio.Server:
    """Start the gRPC server (returns it started; caller owns shutdown).

    With ``native_pipeline`` set (and headers off), ShouldRateLimit runs the
    native columnar path; the Kuadrant service keeps the standard handlers.

    Server reflection is served unconditionally — the reference registers
    tonic-reflection over its vendored descriptor sets the same way
    (envoy_rls/server.rs:232-236,254-263) — via the vendored SDK-free
    implementation in server/reflection.py.
    """
    from .middleware import GrpcRequestIdInterceptor
    from .reflection import make_reflection_handler

    server = grpc.aio.server(interceptors=(GrpcRequestIdInterceptor(),))
    service = RlsService(limiter, metrics, rate_limit_headers, admission)
    envoy_handler, kuadrant_handler = make_rls_handlers(service)
    if native_pipeline is not None and rate_limit_headers == RATE_LIMIT_HEADERS_NONE:
        envoy_handler = make_native_should_rate_limit_handler(
            native_pipeline, admission
        )
    server.add_generic_rpc_handlers((envoy_handler,))
    server.add_generic_rpc_handlers((kuadrant_handler,))
    server.add_generic_rpc_handlers(
        (make_reflection_handler((_ENVOY_SERVICE, _KUADRANT_SERVICE)),)
    )
    if server.add_insecure_port(address) == 0:
        raise RuntimeError(
            f"could not bind RLS gRPC server to {address} (port in use "
            "without SO_REUSEPORT?)"
        )
    await server.start()
    return server


class _ShardAbort(Exception):
    """Raised inside a bridged coroutine to carry ``context.abort``
    semantics back to the sync handler thread."""

    def __init__(self, code, details):
        super().__init__(code, details)
        self.code = code
        self.details = details


class _ShardContextShim:
    """The slice of the async ServicerContext surface the RlsService
    handlers use, backed by a sync context on another thread. ``abort``
    raises (the coroutine ends); the handler thread re-issues it on the
    real context, which is only legal there."""

    __slots__ = ("_context",)

    def __init__(self, context):
        self._context = context

    async def abort(self, code, details=""):
        raise _ShardAbort(code, details)

    def invocation_metadata(self):
        return self._context.invocation_metadata()

    def time_remaining(self):
        return self._context.time_remaining()


class RlsServingShard:
    """One extra serving shard: a SYNC gRPC server (its own C-core
    listener on the SAME address — the kernel spreads accepted
    connections across listeners via SO_REUSEPORT, grpc's default on
    Linux) whose handlers bridge onto the shard's own asyncio loop,
    where the shared limiter's per-loop batchers / submit shards feed
    the one device lane — the Ray serve pattern of per-worker event
    loops over a shared execution lane.

    Sync, not ``grpc.aio``: the aio completion-queue poller is a
    process-global singleton, and a second event loop racing its wakeup
    socket intermittently drops events (observed as stuck RPCs +
    ``BlockingIOError`` in ``PollerCompletionQueue._handle_events``).
    The sync C core gives each shard HTTP/2 framing and proto handling
    on its own threads; only the thin decision bridge crosses into the
    shard loop.

    Construction blocks until the shard's server is listening (raises
    if the bind fails, e.g. on a platform without SO_REUSEPORT)."""

    def __init__(
        self,
        index: int,
        limiter,
        address: str,
        metrics=None,
        rate_limit_headers: str = RATE_LIMIT_HEADERS_NONE,
        native_pipeline=None,
        admission=None,
        workers: int = 16,
    ):
        import asyncio
        import threading
        from concurrent.futures import ThreadPoolExecutor

        from .reflection import make_sync_reflection_handler

        self.index = index
        self.address = address
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._run_loop, name=f"rls-shard-loop-{index}",
            daemon=True,
        )
        self._loop_thread.start()

        service = RlsService(
            limiter, metrics, rate_limit_headers, admission
        )
        self._server = grpc.server(
            ThreadPoolExecutor(
                workers, thread_name_prefix=f"rls-shard-{index}"
            ),
            options=(("grpc.so_reuseport", 1),),
        )
        for handler in self._make_handlers(
            service, rate_limit_headers, native_pipeline, admission
        ):
            self._server.add_generic_rpc_handlers((handler,))
        self._server.add_generic_rpc_handlers(
            (make_sync_reflection_handler(
                (_ENVOY_SERVICE, _KUADRANT_SERVICE)
            ),)
        )
        if self._server.add_insecure_port(address) == 0:
            self.stop(grace=0.0)
            raise RuntimeError(
                f"serving shard {index} could not bind {address} "
                "(SO_REUSEPORT unavailable?)"
            )
        self._server.start()

    def _run_loop(self) -> None:
        import asyncio

        asyncio.set_event_loop(self._loop)
        self._loop.run_forever()
        self._loop.close()

    def _bridge(self, async_fn):
        """Sync handler running ``async_fn(request, shim)`` on the shard
        loop; abort round-trips through _ShardAbort. The client's
        ``x-request-id`` is re-published to the device-plane contextvar
        INSIDE the bridged coroutine (the handler thread's context does
        not cross ``run_coroutine_threadsafe``), so the flight recorder
        correlates shard traffic exactly like the aio interceptor's."""
        import asyncio
        import uuid

        from ..observability.device_plane import set_request_id
        from .middleware import HEADER

        loop = self._loop

        def handler(request, context):
            metadata = dict(context.invocation_metadata() or ())
            request_id = metadata.get(HEADER) or uuid.uuid4().hex

            async def bridged():
                set_request_id(request_id)
                return await async_fn(request, _ShardContextShim(context))

            future = asyncio.run_coroutine_threadsafe(bridged(), loop)
            try:
                response = future.result()
            except _ShardAbort as abort:
                context.set_trailing_metadata(((HEADER, request_id),))
                context.abort(abort.code, abort.details)
                return
            context.send_initial_metadata(((HEADER, request_id),))
            return response

        return handler

    def _make_handlers(
        self, service, rate_limit_headers, native_pipeline, admission
    ):
        req_des = rls_pb2.RateLimitRequest.FromString
        resp_ser = lambda m: m.SerializeToString()
        if (
            native_pipeline is not None
            and rate_limit_headers == RATE_LIMIT_HEADERS_NONE
        ):
            # Raw-bytes hot path: identity (de)serializers, prebuilt
            # response blobs — the same lane the aio server mounts.
            hot = _native_should_rate_limit(native_pipeline, admission)
            envoy = grpc.method_handlers_generic_handler(
                _ENVOY_SERVICE,
                {
                    "ShouldRateLimit": grpc.unary_unary_rpc_method_handler(
                        self._bridge(hot),
                        request_deserializer=None,
                        response_serializer=None,
                    )
                },
            )
        else:
            envoy = grpc.method_handlers_generic_handler(
                _ENVOY_SERVICE,
                {
                    "ShouldRateLimit": grpc.unary_unary_rpc_method_handler(
                        self._bridge(service.should_rate_limit),
                        request_deserializer=req_des,
                        response_serializer=resp_ser,
                    )
                },
            )
        kuadrant = grpc.method_handlers_generic_handler(
            _KUADRANT_SERVICE,
            {
                "CheckRateLimit": grpc.unary_unary_rpc_method_handler(
                    self._bridge(service.check_rate_limit),
                    request_deserializer=req_des,
                    response_serializer=resp_ser,
                ),
                "Report": grpc.unary_unary_rpc_method_handler(
                    self._bridge(service.report),
                    request_deserializer=req_des,
                    response_serializer=resp_ser,
                ),
            },
        )
        return [envoy, kuadrant]

    def stop(self, grace: float = 1.0) -> None:
        try:
            self._server.stop(grace).wait(timeout=10)
        except Exception:
            pass
        if not self._loop.is_closed():
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._loop_thread.join(timeout=10)
