"""Limits YAML file: load, validate, hot reload.

Mirrors the reference's limits-file handling
(/root/reference/limitador-server/src/main.rs:187-246,302-407): the YAML is
a list of limit objects (doc/server/configuration.md:58-105); changes are
re-applied declaratively via ``configure_with`` (counters of surviving
limits are preserved); the watcher tracks the canonical path so kubernetes
ConfigMap symlink flips are caught. The reference uses inotify; here a
polling thread watches (mtime, resolved path) — dependency-free and
equally correct for the ConfigMap case.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional

import yaml

from ..core.cel import ParseError
from ..core.limit import Limit

__all__ = ["load_limits_file", "LimitsFileWatcher"]


class LimitsFileError(Exception):
    pass


def load_limits_file(path: str) -> List[Limit]:
    """Parse + validate the limits YAML; raises LimitsFileError."""
    try:
        with open(path) as f:
            data = yaml.safe_load(f)
    except OSError as exc:
        raise LimitsFileError(f"cannot read limits file {path}: {exc}") from None
    except yaml.YAMLError as exc:
        raise LimitsFileError(f"invalid YAML in {path}: {exc}") from None
    if data is None:
        return []
    if not isinstance(data, list):
        raise LimitsFileError(f"limits file {path} must contain a list")
    limits = []
    for i, entry in enumerate(data):
        if not isinstance(entry, dict):
            raise LimitsFileError(f"limits file {path}: entry {i} not a map")
        try:
            limits.append(Limit.from_dict(entry))
        except (KeyError, TypeError, ValueError, ParseError) as exc:
            raise LimitsFileError(
                f"limits file {path}: entry {i} invalid: {exc}"
            ) from None
    return limits


class LimitsFileWatcher:
    """Polls (resolved path, mtime) and fires ``on_change(loaded)`` — or
    ``on_error(exc)`` — when the file content version changes. ``loader``
    defaults to the limits-YAML parser; pass another callable to watch any
    config file with the same ConfigMap-symlink-aware stamping (the
    reference watches its metric-labels file the same way,
    main.rs:287-300,359-390)."""

    def __init__(
        self,
        path: str,
        on_change: Callable[[List[Limit]], None],
        on_error: Optional[Callable[[Exception], None]] = None,
        poll_interval: float = 1.0,
        loader: Callable[[str], object] = None,
    ):
        self.path = path
        self.on_change = on_change
        self.on_error = on_error
        self.poll_interval = poll_interval
        self.loader = loader or load_limits_file
        self._stamp = self._current_stamp()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.version = 1
        self.errors = 0

    def _current_stamp(self):
        try:
            real = os.path.realpath(self.path)
            return (real, os.stat(real).st_mtime_ns)
        except OSError:
            return (None, None)

    def _tick(self) -> None:
        stamp = self._current_stamp()
        if stamp == self._stamp:
            return
        self._stamp = stamp
        try:
            loaded = self.loader(self.path)
        except Exception as exc:
            self.errors += 1
            if self.on_error:
                self.on_error(exc)
            return
        self.version += 1
        try:
            self.on_change(loaded)
        except Exception as exc:
            # A throwing consumer must not kill the watcher thread — the
            # next edit would then never be observed.
            self.errors += 1
            if self.on_error:
                self.on_error(exc)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            self._tick()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="limits-file-watcher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
