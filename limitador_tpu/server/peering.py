"""Pod peer-forwarding lane + the shard-aware routed frontend.

The host-to-host hop of the pod tier (ISSUE 10): each pod process runs
its own complete ingress stack; a descriptor whose counters another
host owns is forwarded exactly once over a gRPC lane to that owner,
which decides it on ITS collective-free local device path. Locally
owned traffic — the hot path the router maximizes — never touches this
module's network code at all.

The lane reuses the replication broker's session plumbing discipline
(storage/distributed/broker.py): a daemon thread owning its own asyncio
loop, a ``grpc.aio`` server registered through a generic handler (no
codegen — the payload is a self-describing JSON blob), channel-per-peer
with lazy dial and per-call deadlines, and every failure surfaced as a
counted, non-fatal verdict (a dead peer fails THAT request; it never
wedges the serving loop). Unlike the broker this lane is
request/response, so sessions are plain unary calls — no handshake, no
gossip.

``PodFrontend`` wraps the process's limiter with the routing verdict
(routing.PodRouter): LOCAL decides through the wrapped limiter
unchanged; FORWARD/PINNED serialize (namespace, context bindings,
delta) to the owner host and adopt its CheckResult. Attribute access
delegates to the wrapped limiter, so the RLS/HTTP planes and the
metrics wiring see the frontend as the limiter itself;
``library_stats`` additionally carries the ``pod_*`` families.
"""

from __future__ import annotations

import asyncio
import collections
import inspect
import json
import logging
import threading
import time
from typing import Dict, Optional, Tuple

from ..core.cel import Context
from ..core.limit import Namespace
from ..core.limiter import (
    AsyncRateLimiter,
    CheckResult,
    _counters_that_apply,
)
from ..routing import LOCAL, PodRouter, counter_key
from ..storage.base import StorageError

__all__ = ["PeerLane", "PodFrontend", "PEER_SERVICE", "PEER_METHOD"]

log = logging.getLogger("limitador_tpu.pod")

PEER_SERVICE = "limitador.service.pod.v1.PodPeer"
PEER_METHOD = f"/{PEER_SERVICE}/Decide"

#: per-forward deadline: a peer slower than this fails the forward (the
#: caller shields itself; Envoy's failure mode decides the request).
#: Generous enough to survive the owner's first-launch XLA compile of a
#: not-yet-warm batch bucket — a cold peer is slow once, not dead.
FORWARD_TIMEOUT_SECONDS = 10.0

#: forward-latency reservoir size for the pod_peer_p99_ms gauge
_LATENCY_WINDOW = 2048


def _encode_context(ctx: Context) -> dict:
    return {
        "variables": sorted(ctx.variables),
        "bindings": ctx._bindings,
    }


def _decode_context(blob: dict) -> Context:
    ctx = Context()
    ctx.variables = set(blob.get("variables", ()))
    ctx._bindings = dict(blob.get("bindings", {}))
    return ctx


class PeerLane:
    """The host-to-host forwarding lane: serves ``Decide`` for peers and
    dials peers for our own forwards. ``decide_cb`` is an async callable
    ``(namespace, ctx, delta, load, kind) -> CheckResult-or-None`` run
    on the lane loop — the owner-side local decision."""

    def __init__(
        self,
        host_id: int,
        listen_address: str,
        peers: Dict[int, str],
        decide_cb,
    ):
        self.host_id = host_id
        self.listen_address = listen_address
        self.peers = dict(peers)
        self.decide_cb = decide_cb
        self.forwards = 0
        self.served = 0
        self.errors = 0
        # Guards the latency reservoir: forwards append from serving
        # event-loop threads while the Prometheus render thread
        # snapshots it (an unguarded sorted() over a mutating deque
        # raises and would drop the whole library_stats render).
        self._latency_lock = threading.Lock()
        self._latencies_ms = collections.deque(maxlen=_LATENCY_WINDOW)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server = None
        self._channels: dict = {}
        self._stopping = threading.Event()
        self._started = threading.Event()
        self.port: Optional[int] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._thread_main,
            name=f"pod-peer-{self.host_id}",
            daemon=True,
        )
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("pod peer lane failed to start")

    def _thread_main(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._amain())

    async def _amain(self) -> None:
        import grpc

        self._server = grpc.aio.server()
        handler = grpc.method_handlers_generic_handler(
            PEER_SERVICE,
            {
                "Decide": grpc.unary_unary_rpc_method_handler(
                    self._serve_decide,
                    request_deserializer=bytes,
                    response_serializer=bytes,
                )
            },
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(self.listen_address)
        await self._server.start()
        self._started.set()
        while not self._stopping.is_set():
            await asyncio.sleep(0.2)
        for channel, _call in self._channels.values():
            await channel.close()
        await self._server.stop(grace=0.5)

    def stop(self) -> None:
        self._stopping.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- server side ---------------------------------------------------------

    async def _serve_decide(self, blob: bytes, context) -> bytes:
        payload = json.loads(blob.decode())
        self.served += 1
        result = await self.decide_cb(
            payload["ns"],
            _decode_context(payload["ctx"]),
            int(payload["delta"]),
            bool(payload.get("load", False)),
            payload.get("kind", "check_and_update"),
        )
        out: dict = {"ok": True}
        if isinstance(result, CheckResult):
            out["limited"] = bool(result.limited)
            out["name"] = result.limit_name
            out["counters"] = [
                {
                    "max": c.max_value,
                    "remaining": c.remaining,
                    "expires_in": c.expires_in,
                    "window": c.window_seconds,
                    "name": c.limit.name if c.limit is not None else None,
                }
                for c in result.counters
            ]
        return json.dumps(out).encode()

    # -- client side ---------------------------------------------------------

    async def _forward_on_loop(self, host: int, blob: bytes) -> bytes:
        import grpc

        entry = self._channels.get(host)
        if entry is None:
            channel = grpc.aio.insecure_channel(self.peers[host])
            call = channel.unary_unary(
                PEER_METHOD,
                request_serializer=bytes,
                response_deserializer=bytes,
            )
            entry = self._channels[host] = (channel, call)
        _channel, call = entry
        return await call(blob, timeout=FORWARD_TIMEOUT_SECONDS)

    async def forward(
        self,
        host: int,
        namespace: str,
        ctx: Context,
        delta: int,
        load: bool,
        kind: str = "check_and_update",
    ) -> dict:
        """Forward one decision to its owner host (callable from any
        serving event loop; the channel work runs on the lane loop).
        Raises on peer failure after counting it — the caller maps that
        to its shed/unavailable semantics."""
        if host not in self.peers:
            self.errors += 1
            raise RuntimeError(f"no peer lane for pod host {host}")
        blob = json.dumps({
            "ns": str(namespace),
            "ctx": _encode_context(ctx),
            "delta": int(delta),
            "load": bool(load),
            "kind": kind,
            "from": self.host_id,
        }).encode()
        t0 = time.perf_counter()
        fut = asyncio.run_coroutine_threadsafe(
            self._forward_on_loop(host, blob), self._loop
        )
        try:
            raw = await asyncio.wrap_future(fut)
        except Exception:
            self.errors += 1
            raise
        self.forwards += 1
        with self._latency_lock:
            self._latencies_ms.append((time.perf_counter() - t0) * 1e3)
        return json.loads(raw.decode())

    # -- telemetry -----------------------------------------------------------

    def peer_p99_ms(self) -> float:
        with self._latency_lock:
            lat = sorted(self._latencies_ms)
        if not lat:
            return 0.0
        return lat[min(int(0.99 * len(lat)), len(lat) - 1)]

    def stats(self) -> dict:
        return {
            "pod_peer_forwards": self.forwards,
            "pod_peer_served": self.served,
            "pod_peer_errors": self.errors,
            "pod_peer_p99_ms": round(self.peer_p99_ms(), 3),
        }


class PodFrontend:
    """Shard-aware routed frontend over a limiter: decide locally when
    this host owns every counter the request touches, else one
    peer-lane hop to the owner. Used by RlsService/http_api exactly
    like the limiter it wraps (attribute delegation)."""

    #: RlsService awaits check/update calls when this is set even
    #: though we are not an AsyncRateLimiter instance
    is_async_limiter = True

    def __init__(
        self,
        limiter,
        router: PodRouter,
        lane: PeerLane,
        global_namespaces=(),
    ):
        self._limiter = limiter
        self.router = router
        self.lane = lane
        self._global_ns = {str(ns) for ns in global_namespaces}
        self._inner_async = isinstance(limiter, AsyncRateLimiter)
        lane.decide_cb = self._decide_for_peer

    def __getattr__(self, name):
        return getattr(self._limiter, name)

    # -- configuration -------------------------------------------------------

    async def configure_with(self, limits) -> None:
        limits = list(limits)
        self.router.configure(limits, self._global_ns)
        res = self._limiter.configure_with(limits)
        if inspect.isawaitable(res):
            await res

    # -- routing helpers -----------------------------------------------------

    def _plan(self, namespace, ctx) -> Tuple[str, int]:
        # Known cost: the wrapped limiter re-runs this same matching on
        # the LOCAL path (no limiter entry point accepts precomputed
        # counters yet — ROADMAP direction 1 follow-on d).
        keys = [
            counter_key(c)
            for c in _counters_that_apply(
                self._limiter.storage, Namespace.of(namespace), ctx
            )
        ]
        return self.router.plan(str(namespace), keys)

    async def _local_check(self, namespace, ctx, delta, load) -> CheckResult:
        if self._inner_async:
            return await self._limiter.check_rate_limited_and_update(
                namespace, ctx, delta, load
            )
        return self._limiter.check_rate_limited_and_update(
            namespace, ctx, delta, load
        )

    async def _local_is_limited(self, namespace, ctx, delta) -> CheckResult:
        if self._inner_async:
            return await self._limiter.is_rate_limited(namespace, ctx, delta)
        return self._limiter.is_rate_limited(namespace, ctx, delta)

    async def _local_update(self, namespace, ctx, delta) -> None:
        if self._inner_async:
            await self._limiter.update_counters(namespace, ctx, delta)
        else:
            self._limiter.update_counters(namespace, ctx, delta)

    async def _decide_for_peer(
        self, namespace, ctx, delta, load, kind
    ) -> Optional[CheckResult]:
        """Owner-side handler of a forwarded decision: we own it, so it
        runs the LOCAL path directly (no re-routing — a forward is
        always terminal, one hop by construction)."""
        if kind == "is_rate_limited":
            return await self._local_is_limited(namespace, ctx, delta)
        if kind == "update_counters":
            await self._local_update(namespace, ctx, delta)
            return None
        return await self._local_check(namespace, ctx, delta, load)

    @staticmethod
    def _adopt(resp: dict) -> CheckResult:
        """A forwarded decision's CheckResult, with owner-loaded counter
        headers rebuilt as lightweight stand-ins."""
        counters = []
        for c in resp.get("counters", ()):
            counters.append(_ForwardedCounter(
                c.get("max"), c.get("remaining"), c.get("expires_in"),
                c.get("window"), c.get("name"),
            ))
        return CheckResult(
            bool(resp.get("limited", False)), counters, resp.get("name")
        )

    async def _forward(
        self, owner, namespace, ctx, delta, load, kind
    ) -> dict:
        """One peer hop, with failures mapped to StorageError: the
        serving planes (rls.py aborts UNAVAILABLE, http_api answers
        500) already give StorageError the unavailable semantics a
        dead owner host deserves — a raw AioRpcError would surface as
        an unhandled UNKNOWN instead."""
        try:
            return await self.lane.forward(
                owner, namespace, ctx, delta, load, kind=kind
            )
        except Exception as exc:
            raise StorageError(
                f"pod peer host {owner} unavailable: {exc}"
            ) from exc

    # -- the limiter surface -------------------------------------------------

    async def check_rate_limited_and_update(
        self, namespace, ctx, delta: int, load_counters: bool = False
    ) -> CheckResult:
        verdict, owner = self._plan(namespace, ctx)
        if verdict == LOCAL:
            return await self._local_check(
                namespace, ctx, delta, load_counters
            )
        resp = await self._forward(
            owner, namespace, ctx, delta, load_counters,
            kind="check_and_update",
        )
        return self._adopt(resp)

    async def is_rate_limited(self, namespace, ctx, delta: int) -> CheckResult:
        verdict, owner = self._plan(namespace, ctx)
        if verdict == LOCAL:
            return await self._local_is_limited(namespace, ctx, delta)
        resp = await self._forward(
            owner, namespace, ctx, delta, False, kind="is_rate_limited"
        )
        return self._adopt(resp)

    async def update_counters(self, namespace, ctx, delta: int) -> None:
        verdict, owner = self._plan(namespace, ctx)
        if verdict == LOCAL:
            await self._local_update(namespace, ctx, delta)
            return
        await self._forward(
            owner, namespace, ctx, delta, False, kind="update_counters"
        )

    # -- telemetry -----------------------------------------------------------

    def library_stats(self) -> dict:
        inner = getattr(self._limiter, "library_stats", None)
        stats = dict(inner()) if callable(inner) else {}
        stats.update(self.router.stats())
        stats.update(self.lane.stats())
        return stats

    def close_pod(self) -> None:
        self.lane.stop()


class _ForwardedLimit:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name


class _ForwardedCounter:
    """Header stand-in for a counter loaded on the owner host (exactly
    the fields CheckResult.response_header reads)."""

    __slots__ = (
        "max_value", "remaining", "expires_in", "window_seconds", "limit",
    )

    def __init__(self, max_value, remaining, expires_in, window, name):
        self.max_value = max_value
        self.remaining = remaining
        self.expires_in = expires_in
        self.window_seconds = window
        self.limit = _ForwardedLimit(name)
