"""Pod peer-forwarding lane + the shard-aware routed frontend.

The host-to-host hop of the pod tier (ISSUE 10): each pod process runs
its own complete ingress stack; a descriptor whose counters another
host owns is forwarded exactly once over a gRPC lane to that owner,
which decides it on ITS collective-free local device path. Locally
owned traffic — the hot path the router maximizes — never touches this
module's network code at all.

The lane reuses the replication broker's session plumbing discipline
(storage/distributed/broker.py): a daemon thread owning its own asyncio
loop, a ``grpc.aio`` server registered through a generic handler (no
codegen — the payload is a self-describing JSON blob), channel-per-peer
with lazy dial and per-call deadlines, and every failure surfaced as a
counted, non-fatal verdict (a dead peer fails THAT request; it never
wedges the serving loop). Unlike the broker this lane is
request/response, so sessions are plain unary calls — no handshake, no
gossip.

``PodFrontend`` wraps the process's limiter with the routing verdict
(routing.PodRouter): LOCAL decides through the wrapped limiter
unchanged; FORWARD/PINNED serialize (namespace, context bindings,
delta) to the owner host and adopt its CheckResult. Attribute access
delegates to the wrapped limiter, so the RLS/HTTP planes and the
metrics wiring see the frontend as the limiter itself;
``library_stats`` additionally carries the ``pod_*`` families.

The pod resilience plane (ISSUE 11) layers three mechanisms over the
lane so a dead owner host degrades instead of hard-failing its key
range (docs/configuration.md "Pod resilience"):

* **Peer health** (:class:`PeerHealth`): per-peer up/suspect/down from
  consecutive forward failures and deadline misses, background probes
  on the lane's daemon loop, and a channel re-dial on every trip — a
  peer restarted on the same address gets a fresh dial instead of the
  stale cached channel (the PR 10 bug).
* **Retry + hedging**: one jittered-backoff retry for idempotent check
  forwards once a peer is suspect, and an opt-in hedge
  (``--pod-hedge-ms``) that races a second attempt on a fresh channel
  when an in-flight forward outlasts both the configured floor and the
  tracked peer p99 — both budgeted against the forward deadline so a
  retry can never outlive the request.
* **Degraded-owner failover** (:class:`PodFrontend` +
  ``--pod-degraded-mode``): forward failures feed a per-peer circuit
  breaker (the admission plane's closed/open/half-open core); while it
  is away from closed, that owner's forwarded traffic is decided
  against a local exact stand-in (``storage/failover.py``) that
  journals every admitted delta. When the background probe finds the
  peer serving again, the journal replays to the owner through the
  lane into its storage's ``apply_deltas`` contract, the stand-in
  drains, and routing flips back — zero admitted updates are lost
  across the partition window, and over-admission is bounded by one
  window budget per counter (docs/serving-model.md).

The pod observability plane (ISSUE 12) makes the pod the unit of
observation, not just of serving:

* **Cross-host decision tracing**: a forward carries the originating
  ``x-request-id`` contextvar and the W3C trace context in its gRPC
  metadata; the owner stamps the id into ITS flight-recorder entries
  (and offers one itself when a recorder is attached) and opens a
  ``pod_peer_decide`` span LINKED to the origin's span. The owner
  reports its decide time back, and the origin records the per-hop
  breakdown (queue / serialize / wire / remote_decide) into the
  ``pod_hop_phase_ms`` family plus the process flight recorder
  (observability/pod_plane.py).
* **Federated signals**: each host's ``ControlSignals`` column is
  exchanged piggybacked on the probe cadence (``kind: "signals"`` —
  never on the decision path) and joined by ``PodSignalAggregator``
  into the ``GET /debug/pod`` snapshot.
* **Structured pod events**: every health transition, breaker
  transition, degraded enter/exit, journal replay begin/end, routing
  epoch bump, channel re-dial and hedge outcome lands as a typed,
  sequenced event in the ``PodEventLog`` ring (``GET /debug/events``,
  ``pod_events_total{kind}`` — observability/events.py).
"""

from __future__ import annotations

import asyncio
import base64
import collections
import hashlib
import inspect
import json
import logging
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..admission.breaker import BreakerState, CircuitBreaker
from ..core.cel import Context
from ..core.counter import Counter
from ..core.limit import Limit, Namespace
from ..core.limiter import (
    AsyncRateLimiter,
    CheckResult,
    _counters_that_apply,
)
from ..observability.device_plane import (
    current_request_id,
    set_request_id,
)
from ..observability.events import PodEventLog
from ..observability.pod_plane import PodHopRecorder, PodSignalAggregator
from ..observability.tracing import hop_trace_metadata, peer_decide_span
from ..routing import LOCAL, PodRouter, counter_key
from ..storage.base import StorageError
from ..storage.failover import FailoverStore

__all__ = [
    "PeerLane",
    "PodFrontend",
    "PodResilience",
    "PeerHealth",
    "PeerState",
    "FaultInjector",
    "PEER_SERVICE",
    "PEER_METHOD",
    "METRIC_FAMILIES",
]

log = logging.getLogger("limitador_tpu.pod")

PEER_SERVICE = "limitador.service.pod.v1.PodPeer"
PEER_METHOD = f"/{PEER_SERVICE}/Decide"

#: per-forward deadline: a peer slower than this fails the forward (the
#: caller shields itself; Envoy's failure mode decides the request).
#: Generous enough to survive the owner's first-launch XLA compile of a
#: not-yet-warm batch bucket — a cold peer is slow once, not dead.
FORWARD_TIMEOUT_SECONDS = 10.0

#: forward-latency reservoir size for the pod_peer_p99_ms gauge
_LATENCY_WINDOW = 2048

#: forward kinds safe to retry/hedge: a duplicated check at worst
#: double-counts one delta (conservative for a limiter — it can only
#: under-admit; a duplicated bulk batch double-counts one batch's
#: deltas, the same direction); update_counters and apply_deltas carry
#: their own replay semantics and are never retried by the lane.
RETRYABLE_KINDS = frozenset({
    "check_and_update", "is_rate_limited", "ping", "bulk_decide",
    # NOT "migrate": slice batches ride admin_call (no lane retry) —
    # the resize coordinator owns its own bounded retry loop, and the
    # receiver ledger makes re-delivery idempotent either way.
})

#: metric families this subsystem owns (cross-checked against
#: observability/metrics.py by the analysis registry pass): peer health
#: state + retry/hedge traffic from the lane, degraded-owner failover
#: from the frontend — all polled off library_stats at render time.
METRIC_FAMILIES = (
    "peer_health_state",
    "peer_health_retries",
    "peer_health_hedges_won",
    "peer_health_hedges_lost",
    "peer_health_redials",
    "peer_health_probes",
    "pod_failover_degraded_decisions",
    "pod_failover_journal_depth",
    "pod_failover_breaker_open",
    "pod_failover_reconciles",
    "pod_failover_replayed_deltas",
    "pod_failover_reconcile_seconds",
    "pod_failover_seconds",
    # pod fast path (ISSUE 13): the bulk-forward lane — foreign-owned
    # hot-lane rows ride ONE RPC per (owner, flush) instead of one per
    # decision; batches/rows give the mean bulk batch size.
    "pod_bulk_forward_batches",
    "pod_bulk_forward_rows",
    "pod_bulk_served_rows",
)

#: the typed, rerouteable status a wrong-epoch forward is rejected with
#: (ISSUE 15): the owner-side gate answers this instead of deciding a
#: key it no longer (or does not yet) own; the origin adopts the newer
#: topology when one is attached and re-plans the request.
STALE_EPOCH = "stale_epoch"


def _encode_context(ctx: Context) -> dict:
    return {
        "variables": sorted(ctx.variables),
        "bindings": ctx._bindings,
    }


def _decode_context(blob: dict) -> Context:
    ctx = Context()
    ctx.variables = set(blob.get("variables", ()))
    ctx._bindings = dict(blob.get("bindings", {}))
    return ctx


def _counter_to_wire(counter: Counter, delta: int) -> dict:
    """JSON-safe identity of a journaled counter delta, so the owner
    rebuilds a Counter that hashes identically in its own storage.
    ``policy`` is identity-bearing (core/limit.py: a fixed-window and a
    token-bucket limit with equal parameters are DIFFERENT limits) —
    dropping it would replay a token-bucket journal onto a phantom
    fixed-window counter."""
    limit = counter.limit
    return {
        "ns": str(limit.namespace),
        "max": limit.max_value,
        "seconds": limit.seconds,
        "conditions": sorted(c.source for c in limit.conditions),
        "variables": sorted(v.source for v in limit.variables),
        "name": limit.name,
        "id": limit.id,
        "policy": limit.policy,
        "vars": sorted(counter.set_variables.items()),
        "delta": int(delta),
    }


def _counter_from_wire(blob: dict) -> Tuple[Counter, int]:
    limit = Limit(
        blob["ns"], blob["max"], blob["seconds"],
        blob.get("conditions", ()), blob.get("variables", ()),
        name=blob.get("name"), id=blob.get("id"),
        policy=blob.get("policy", "fixed_window"),
    )
    return Counter(limit, dict(blob.get("vars", ()))), int(blob["delta"])


def _wire_request_id(request_id: Optional[str]) -> Optional[str]:
    """The id as it may ride gRPC metadata. The contextvar value
    originates from an UNVALIDATED client header (middleware.py echoes
    whatever arrived); gRPC rejects non-printable/non-ASCII metadata
    values at call time, and that rejection would fail the forward and
    feed peer-health accounting — a single misbehaving client must not
    get a healthy peer marked suspect. Non-conforming characters are
    dropped (correlation still works on the surviving prefix); an id
    that sanitizes to nothing stays off the wire."""
    if not request_id:
        return None
    rid = str(request_id)[:128]
    if not (rid.isascii() and rid.isprintable()):
        rid = "".join(
            c for c in rid if c.isascii() and c.isprintable()
        )[:128]
    return rid or None


def _is_deadline_miss(exc: BaseException) -> bool:
    if isinstance(exc, (TimeoutError, asyncio.TimeoutError)):
        return True
    return "DEADLINE_EXCEEDED" in f"{exc}"


class PodResilience:
    """Pod resilience knobs (server flags ``--pod-hedge-ms``,
    ``--pod-peer-breaker-*``, ``--pod-degraded-mode``; env ``TPU_POD_*``
    — docs/configuration.md "Pod resilience"). :meth:`legacy` is the
    PR 10 posture every direct construction defaults to: no retry, no
    hedge, no breaker/failover — a peer failure fails that request."""

    def __init__(
        self,
        degraded: bool = True,
        retry: bool = True,
        hedge_ms: float = 0.0,
        retry_backoff_ms: float = 1.0,
        breaker_failures: int = 3,
        breaker_reset_s: float = 2.0,
        suspect_after: int = 1,
        down_after: int = 3,
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 1.0,
        deadline_s: float = FORWARD_TIMEOUT_SECONDS,
        journal_cache: int = 100_000,
    ):
        self.degraded = bool(degraded)
        self.retry = bool(retry)
        self.hedge_ms = float(hedge_ms)
        self.retry_backoff_ms = float(retry_backoff_ms)
        self.breaker_failures = max(int(breaker_failures), 1)
        self.breaker_reset_s = float(breaker_reset_s)
        self.suspect_after = max(int(suspect_after), 1)
        self.down_after = max(int(down_after), self.suspect_after)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.deadline_s = float(deadline_s)
        self.journal_cache = int(journal_cache)

    @classmethod
    def legacy(cls) -> "PodResilience":
        return cls(degraded=False, retry=False, hedge_ms=0.0)


class PeerState:
    UP = "up"
    SUSPECT = "suspect"
    DOWN = "down"

    #: gauge encoding for peer_health_state
    GAUGE = {UP: 0, SUSPECT: 1, DOWN: 2}


class PeerHealth:
    """Per-peer up/suspect/down from consecutive failures. Thread-safe:
    forwards fail from serving event loops, probes succeed on the lane
    loop, recovery completes on its own thread. Transitions are
    returned to the caller (never called back under the lock) so the
    lane can re-dial exactly once per trip."""

    def __init__(
        self, peers, suspect_after: int = 1, down_after: int = 3
    ):
        self.suspect_after = max(int(suspect_after), 1)
        self.down_after = max(int(down_after), self.suspect_after)
        self._health_lock = threading.Lock()
        self._state: Dict[int, str] = {p: PeerState.UP for p in peers}
        self._failures: Dict[int, int] = {p: 0 for p in peers}
        self.transitions = 0
        self.deadline_misses = 0

    def state(self, peer: int) -> str:
        with self._health_lock:
            return self._state.get(peer, PeerState.UP)

    def states(self) -> Dict[int, int]:
        """peer -> gauge encoding (rendered as peer_health_state)."""
        with self._health_lock:
            return {
                p: PeerState.GAUGE[s] for p, s in self._state.items()
            }

    def record_failure(
        self, peer: int, deadline_miss: bool = False
    ) -> Optional[str]:
        """Count one failed forward/probe; returns the new state when
        this call transitioned the peer (the lane re-dials on it)."""
        with self._health_lock:
            if peer not in self._state:
                return None
            if deadline_miss:
                self.deadline_misses += 1
            self._failures[peer] = self._failures.get(peer, 0) + 1
            fails = self._failures[peer]
            new = (
                PeerState.DOWN if fails >= self.down_after
                else PeerState.SUSPECT if fails >= self.suspect_after
                else PeerState.UP
            )
            if new == self._state[peer]:
                return None
            self._state[peer] = new
            self.transitions += 1
            return new

    def set_peers(self, peers) -> None:
        """Adopt a new peer set (live membership change, ISSUE 15):
        new peers start UP with a clean failure count; departed peers
        drop out of the map (their forwards stop existing)."""
        with self._health_lock:
            peers = set(peers)
            for peer in peers - set(self._state):
                self._state[peer] = PeerState.UP
                self._failures[peer] = 0
            for peer in set(self._state) - peers:
                self._state.pop(peer, None)
                self._failures.pop(peer, None)

    def record_success(self, peer: int) -> Optional[str]:
        with self._health_lock:
            if peer not in self._state:
                return None
            self._failures[peer] = 0
            if self._state[peer] == PeerState.UP:
                return None
            self._state[peer] = PeerState.UP
            self.transitions += 1
            return PeerState.UP


class FaultInjector:
    """Deterministic per-peer fault shim for the pod chaos harness.

    Applied on the lane loop just before a forward/probe attempt dials
    its peer, so every failure mode exercises the REAL resilience path
    (health trips, retries, breaker, failover). Modes:

    * ``drop``      — the dial fails instantly (ConnectionError);
    * ``error``     — the call fails instantly (RuntimeError);
    * ``delay``     — the call is delayed ``delay_ms`` then proceeds;
    * ``blackhole`` — the call consumes its whole deadline and times
      out (the pathological stall the hedge exists for).

    Env-seeded for subprocess drills: ``TPU_POD_FAULTS`` is a
    comma-separated list of ``peer:mode[:probability[:times]]`` rules
    (``1:drop``, ``1:error:0.5``, ``0:delay:1:3``), ``TPU_POD_FAULT_SEED``
    seeds the probability draws so a drill replays byte-identically,
    and ``TPU_POD_FAULT_DELAY_MS`` sets the delay-mode latency."""

    MODES = ("drop", "delay", "error", "blackhole")

    def __init__(self, seed: int = 0, delay_ms: float = 100.0):
        self._rng = random.Random(seed)
        self.delay_ms = float(delay_ms)
        # peer -> [mode, probability, remaining_times (None = forever)]
        self._rules: Dict[int, list] = {}
        self.injected = 0

    @classmethod
    def from_env(cls, env=None) -> "FaultInjector":
        env = os.environ if env is None else env
        injector = cls(
            seed=int(env.get("TPU_POD_FAULT_SEED", "0") or 0),
            delay_ms=float(env.get("TPU_POD_FAULT_DELAY_MS", "100") or 100),
        )
        spec = env.get("TPU_POD_FAULTS", "")
        for rule in spec.split(","):
            rule = rule.strip()
            if not rule:
                continue
            parts = rule.split(":")
            if len(parts) < 2 or parts[1] not in cls.MODES:
                raise ValueError(
                    f"TPU_POD_FAULTS rule '{rule}' is not "
                    "peer:mode[:probability[:times]] with mode in "
                    f"{cls.MODES}"
                )
            injector.set_fault(
                int(parts[0]), parts[1],
                p=float(parts[2]) if len(parts) > 2 else 1.0,
                times=int(parts[3]) if len(parts) > 3 else None,
            )
        return injector

    def set_fault(
        self, peer: int, mode: str, p: float = 1.0,
        times: Optional[int] = None,
    ) -> None:
        if mode not in self.MODES:
            raise ValueError(f"unknown fault mode '{mode}'")
        self._rules[int(peer)] = [mode, float(p), times]

    def clear(self, peer: Optional[int] = None) -> None:
        if peer is None:
            self._rules.clear()
        else:
            self._rules.pop(int(peer), None)

    def verdict(self, peer: int) -> Optional[str]:
        """The fault (or None) this attempt draws — deterministic under
        a fixed seed and call sequence."""
        rule = self._rules.get(int(peer))
        if rule is None:
            return None
        mode, p, times = rule
        if times is not None and times <= 0:
            return None
        if p < 1.0 and self._rng.random() >= p:
            return None
        if times is not None:
            rule[2] = times - 1
        self.injected += 1
        return mode

    async def apply(self, peer: int, timeout: float) -> None:
        """Raise/delay per the drawn verdict (lane loop only)."""
        mode = self.verdict(peer)
        if mode is None:
            return
        if mode == "drop":
            raise ConnectionError(f"injected drop for peer {peer}")
        if mode == "error":
            raise RuntimeError(f"injected error for peer {peer}")
        if mode == "delay":
            await asyncio.sleep(self.delay_ms / 1e3)
            return
        # blackhole: the peer never answers — consume the deadline
        await asyncio.sleep(max(float(timeout), 0.0))
        raise TimeoutError(f"injected blackhole for peer {peer}")


class PeerLane:
    """The host-to-host forwarding lane: serves ``Decide`` for peers and
    dials peers for our own forwards. ``decide_cb`` is an async callable
    ``(namespace, ctx, delta, load, kind) -> CheckResult-or-None`` run
    on the lane loop — the owner-side local decision. ``apply_cb`` (set
    by the frontend) applies a recovered peer's journal replay into the
    local storage's ``apply_deltas`` contract."""

    def __init__(
        self,
        host_id: int,
        listen_address: str,
        peers: Dict[int, str],
        decide_cb,
        resilience: Optional[PodResilience] = None,
    ):
        self.host_id = host_id
        self.listen_address = listen_address
        self.peers = dict(peers)
        self.decide_cb = decide_cb
        self.apply_cb: Optional[Callable[[list], int]] = None
        #: async callable (blobs) -> [response bytes or None] run on the
        #: lane loop — the owner side of a bulk forward (ISSUE 13). None
        #: per row means "could not decide terminally" (the origin falls
        #: back to its per-request hop). Wired by PodFrontend.
        #: attach_pipeline.
        self.bulk_cb = None
        #: elastic pod (ISSUE 15) attach points, all wired by the
        #: resize coordinator; None = the PR 14 wire format and serve
        #: path, byte-identical. ``epoch_provider`` -> current topology
        #: epoch (stamped on forwards, gated on serves);
        #: ``stale_info_provider`` -> the topology/peers blob a stale
        #: rejection carries so a behind origin can adopt;
        #: ``migrate_cb(payload) -> dict`` applies one migrated slice
        #: batch (blocking — run off-loop); ``resize_cb(payload) ->
        #: dict`` answers resize control ops (fast, lane loop).
        self.epoch_provider: Optional[Callable[[], int]] = None
        self.stale_info_provider: Optional[Callable[[], dict]] = None
        self.migrate_cb = None
        self.resize_cb = None
        #: fast-join plane (ISSUE 18) attach points, None = PR 17 wire
        #: and serve path byte-identical. ``plan_seed_cb(payload) ->
        #: dict`` imports a shipped plan-cache seed (blocking cache
        #: work — run off-loop); ``join_cb(payload) -> dict`` answers
        #: join control ops on the coordinator (fast, lane loop);
        #: ``psum_share_cb(host, raw)`` delivers a peer's published
        #: psum partials to the PeerPsumTransport fold (dict store —
        #: inline).
        self.plan_seed_cb = None
        self.join_cb = None
        self.psum_share_cb = None
        #: callable(resp dict): a forward came back stale_epoch — the
        #: origin-side adoption hook (coordinator.adopt_remote)
        self.on_stale = None
        self.stale_rejects = 0
        #: sync callable (host) -> bool run on a recovery thread when a
        #: background probe finds a non-up peer answering again; True
        #: marks the peer up (the frontend replays its journal first)
        self.on_peer_recovered: Optional[Callable[[int], bool]] = None
        #: optional (host) -> bool: the frontend answers True while the
        #: host still needs recovery work (breaker away from closed, or
        #: a journal awaiting replay) even though its HEALTH is up — a
        #: sub-threshold failure journals a delta without downing the
        #: peer, and that delta must still drain
        self.probe_needed: Optional[Callable[[int], bool]] = None
        self.cfg = resilience or PodResilience.legacy()
        self.health = PeerHealth(
            self.peers,
            suspect_after=self.cfg.suspect_after,
            down_after=self.cfg.down_after,
        )
        # Fault shim: armed rules must be LOUD (an ambient TPU_POD_FAULTS
        # leaked from a drill runbook would otherwise silently degrade
        # live traffic), and a malformed spec must not abort a pod
        # host's boot.
        try:
            self.faults = FaultInjector.from_env()
        except ValueError as exc:
            log.warning(f"ignoring malformed TPU_POD_FAULTS: {exc}")
            self.faults = FaultInjector()
        if self.faults._rules:
            log.warning(
                "pod fault injection ARMED (TPU_POD_FAULTS): "
                f"{self.faults._rules}"
            )
        self.forwards = 0
        self.served = 0
        self.errors = 0
        self.bulk_forwards = 0
        self.bulk_forward_rows = 0
        self.bulk_served_rows = 0
        self.retries = 0
        self.hedges_won = 0
        self.hedges_lost = 0
        self.redials = 0
        self.probes = 0
        # Pod observability plane (ISSUE 12) attach points, all
        # optional (None = zero cost): the frontend wires them up.
        #: PodEventLog — typed lane events (health trips, redials,
        #: hedge outcomes)
        self.events: Optional[PodEventLog] = None
        #: callable(host, request_id, namespace, total_s, phases_s):
        #: per-hop breakdown of one completed forward
        self.on_hop = None
        #: callable() -> dict: this host's signal column, exchanged
        #: with every peer on the probe cadence
        self.signals_provider = None
        #: callable(host, payload): a peer's signal column arrived
        self.on_peer_signals = None
        #: DeviceStatsRecorder (or bare FlightRecorder): forwarded
        #: decisions WE decide for peers land here with the
        #: originating request id
        self.recorder = None
        #: flight.FlightRecorder (ISSUE 16): the always-on exemplar
        #: rings — owner-side decides tap it, and the ``flight`` admin
        #: kind serves our frozen rings to a triggered peer building a
        #: pod-correlated incident bundle
        self.flight = None
        self.signal_exchanges = 0
        self.signal_exchange_failures = 0
        self._signal_inflight: set = set()
        # Guards the latency reservoir: forwards append from serving
        # event-loop threads while the Prometheus render thread
        # snapshots it (an unguarded sorted() over a mutating deque
        # raises and would drop the whole library_stats render).
        self._latency_lock = threading.Lock()
        self._latencies_ms = collections.deque(maxlen=_LATENCY_WINDOW)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server = None
        self._channels: dict = {}
        self._recovering: set = set()
        self._probing: set = set()
        self._stopping = threading.Event()
        self._started = threading.Event()
        self.port: Optional[int] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._thread_main,
            name=f"pod-peer-{self.host_id}",
            daemon=True,
        )
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("pod peer lane failed to start")

    def _thread_main(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._amain())

    async def _amain(self) -> None:
        import grpc

        self._server = grpc.aio.server()
        handler = grpc.method_handlers_generic_handler(
            PEER_SERVICE,
            {
                "Decide": grpc.unary_unary_rpc_method_handler(
                    self._serve_decide,
                    request_deserializer=bytes,
                    response_serializer=bytes,
                )
            },
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(self.listen_address)
        await self._server.start()
        self._started.set()
        # Background probes ride the existing daemon loop: while the
        # frontend degrades a down owner's traffic, this is the only
        # path that notices the owner serving again and kicks off the
        # journal replay — recovery never depends on live traffic.
        next_probe = self._loop.time()
        while not self._stopping.is_set():
            await asyncio.sleep(0.1)
            if not self.peers:
                continue
            now = self._loop.time()
            if now < next_probe:
                continue
            next_probe = now + self.cfg.probe_interval_s
            if self.cfg.degraded:
                for host in list(self.peers):
                    if self.health.state(host) != PeerState.UP or (
                        self.probe_needed is not None
                        and self.probe_needed(host)
                    ):
                        asyncio.ensure_future(self._probe(host))
            # Federated signal exchange (ISSUE 12): piggybacked on the
            # SAME cadence — the only background chatter frequency the
            # pod has — and only with peers believed up (a down peer's
            # column goes stale, which is itself the signal; probes own
            # detecting its return).
            if self.signals_provider is not None:
                for host in list(self.peers):
                    if self.health.state(host) == PeerState.UP:
                        asyncio.ensure_future(
                            self._exchange_signals(host)
                        )
        for channel, _call in self._channels.values():
            await channel.close()
        await self._server.stop(grace=0.5)

    def stop(self) -> None:
        self._stopping.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def set_peers(self, peers: Dict[int, str]) -> None:
        """Adopt a new peer map on a running lane (live membership
        change, ISSUE 15). Safe from any thread: the dict swap is
        atomic, health adds/removes under its own lock, and departed
        peers' cached channels are closed on the lane loop (an
        in-flight call on one surfaces as the usual connection
        failure)."""
        peers = {int(h): str(addr) for h, addr in peers.items()}
        peers.pop(self.host_id, None)
        old = self.peers
        self.peers = peers
        self.health.set_peers(peers)
        # a host id whose ADDRESS moved (a standby adopting a dead
        # member's id, ISSUE 18) must drop its cached channel too, or
        # every call to the id keeps dialing the corpse
        stale = [h for h in old if h not in peers] + [
            h for h, addr in peers.items()
            if h in old and old[h] != addr
        ]
        if stale and self._loop is not None:
            def _close_stale():
                for host in stale:
                    entry = self._channels.pop(host, None)
                    if entry is not None:
                        asyncio.ensure_future(entry[0].close())
            self._loop.call_soon_threadsafe(_close_stale)

    def admin_call(
        self, host: int, payload: dict, timeout: float = 5.0
    ) -> dict:
        """One blocking control-plane RPC to a peer (resize protocol —
        coordinator/recovery threads only, NEVER a serving loop).
        Raises on peer failure; the caller owns retries/abort."""
        blob = json.dumps(payload).encode()
        fut = asyncio.run_coroutine_threadsafe(
            self._attempt(host, blob, timeout), self._loop
        )
        return json.loads(fut.result(timeout + 1.0).decode())

    # -- server side ---------------------------------------------------------

    def _stale_response(self) -> bytes:
        """The typed rerouteable rejection a wrong-epoch forward gets
        (ISSUE 15): carries our topology epoch and (when the resize
        plane is armed) the full topology/peers blob so a behind
        origin can adopt and re-plan instead of failing the request."""
        self.stale_rejects += 1
        provider = self.epoch_provider
        out = {
            "ok": False,
            STALE_EPOCH: True,
            "tepoch": int(provider()) if provider is not None else 0,
        }
        info_provider = self.stale_info_provider
        if info_provider is not None:
            try:
                out.update(info_provider() or {})
            except Exception:
                pass
        return json.dumps(out).encode()

    def _epoch_mismatch(self, payload: dict) -> bool:
        """The owner-side epoch gate: ONE int compare per payload (per
        batch on the bulk path — never per row), and only when both
        sides are resize-armed; un-stamped payloads (PR 14 peers,
        resize off) serve unconditionally."""
        provider = self.epoch_provider
        if provider is None or "tepoch" not in payload:
            return False
        return int(payload["tepoch"]) != int(provider())

    async def _serve_decide(self, blob: bytes, context) -> bytes:
        payload = json.loads(blob.decode())
        kind = payload.get("kind", "check_and_update")
        if kind == "ping":
            return json.dumps({"ok": True, "pong": True}).encode()
        if kind == "resize_admin":
            # Elastic-pod control plane (ISSUE 15): propose/commit/
            # status/abort ops answered by the coordinator. Handlers
            # are fast (lock + state flip; migration work happens on
            # coordinator threads) so they run inline on the lane loop.
            handler = self.resize_cb
            if handler is None:
                return json.dumps({
                    "ok": False, "error": "pod resize not armed",
                }).encode()
            try:
                out = handler(payload) or {}
            except Exception as exc:
                out = {"ok": False, "error": f"{exc}"[:200]}
            return json.dumps(out).encode()
        if kind == "join_admin":
            # Fast-join control plane (ISSUE 18): limits ship /
            # membership ops answered by the standby's joiner or the
            # coordinator. Fast (state flip) — inline on the lane loop.
            handler = self.join_cb
            if handler is None:
                return json.dumps({
                    "ok": False, "error": "fast join not armed",
                }).encode()
            try:
                out = handler(payload)
                if inspect.isawaitable(out):
                    # the joiner's "limits" op runs configure_with —
                    # a coroutine on this very loop
                    out = await out
                out = out or {}
            except Exception as exc:
                out = {"ok": False, "error": f"{exc}"[:200]}
            return json.dumps(out).encode()
        if kind == "plan_seed":
            # A peer shipping its plan-cache seed state (ISSUE 18).
            # NOT topology-epoch gated: the seed lands on a joiner
            # mid-adoption, and staleness is decided where it belongs —
            # the cache's put() discards entries whose LIMITS epoch
            # moved (a reload racing the ship). Blocking cache/slot
            # work — off-loop like migrate.
            handler = self.plan_seed_cb
            if handler is None:
                return json.dumps({
                    "ok": False, "error": "fast join not armed",
                }).encode()
            out = await asyncio.get_running_loop().run_in_executor(
                None, handler, payload
            )
            return json.dumps(out or {"ok": True}).encode()
        if kind == "psum_share":
            # A peer's published psum partials (ISSUE 18, per-host
            # meshes): one dict store — inline, never fails the RPC.
            hook = self.psum_share_cb
            if hook is not None:
                try:
                    hook(
                        int(payload.get("from", -1)),
                        base64.b64decode(payload.get("payload") or b""),
                    )
                except Exception:
                    pass
            return json.dumps({"ok": True}).encode()
        if kind == "migrate":
            # One migrated slice batch (absolute counter values; the
            # receiver applies diffs against its transition ledger).
            # Epoch-gated: a migrate stamped for a transition we have
            # already left (aborted or completed past it) must not
            # seed counters we do not own.
            handler = self.migrate_cb
            if handler is None:
                return json.dumps({
                    "ok": False, "error": "pod resize not armed",
                }).encode()
            if self._epoch_mismatch(payload):
                return self._stale_response()
            out = await asyncio.get_running_loop().run_in_executor(
                None, handler, payload
            )
            return json.dumps(out or {"ok": True}).encode()
        if kind == "signals":
            # Federated signal exchange (ISSUE 12): ingest the caller's
            # column, answer with ours — symmetric, one RPC per pair
            # per cadence direction, never on the decision path.
            hook = self.on_peer_signals
            if hook is not None:
                try:
                    hook(
                        int(payload.get("from", -1)),
                        payload.get("signals") or {},
                    )
                except Exception:
                    pass  # a bad column must not fail the exchange
            mine: dict = {}
            provider = self.signals_provider
            if provider is not None:
                try:
                    mine = provider()
                except Exception:
                    mine = {}
            return json.dumps({"ok": True, "signals": mine}).encode()
        if kind == "flight":
            # Pod-correlated autopsy (ISSUE 16): a triggered peer asks
            # for our rings over its incident window. contribute() is
            # one lock + list copies — fine inline on the lane loop.
            flight = self.flight
            if flight is None:
                return json.dumps({
                    "ok": False,
                    "error": "no flight recorder attached",
                }).encode()
            return json.dumps({
                "ok": True,
                "flight": flight.contribute(
                    payload.get("t0"), payload.get("t1")
                ),
            }).encode()
        if kind == "bulk_decide":
            # Pod fast path (ISSUE 13): a peer's flush of foreign-owned
            # raw request blobs, decided here in ONE local bulk pass
            # (the zero-Python lane at bulk batch sizes). The hop's
            # trace metadata rides exactly like a single forward: adopt
            # the origin's request id so owner-side flight entries and
            # spans still correlate.
            handler = self.bulk_cb
            if handler is None:
                raise RuntimeError(
                    "pod peer lane has no bulk_decide handler (native "
                    "pipeline not attached)"
                )
            if self._epoch_mismatch(payload):
                # a whole bulk batch routed by a dead topology: reject
                # once (one compare per BATCH); the origin re-plans
                # every row through its per-request path
                return self._stale_response()
            meta = {}
            try:
                meta = dict(context.invocation_metadata() or ())
            except Exception:
                meta = {}
            rid = meta.get("x-request-id")
            if rid is not None:
                set_request_id(str(rid))
            blobs = [
                base64.b64decode(b) for b in payload.get("blobs", ())
            ]
            self.bulk_served_rows += len(blobs)
            self.served += 1
            t_decide = time.perf_counter()
            with peer_decide_span("_bulk", rid, meta):
                payloads = await handler(blobs)
            decide_s = time.perf_counter() - t_decide
            return json.dumps({
                "ok": True,
                "decide_ns": int(decide_s * 1e9),
                "payloads": [
                    None if p is None else base64.b64encode(p).decode()
                    for p in payloads
                ],
            }).encode()
        if kind == "apply_deltas":
            if self.apply_cb is None:
                raise RuntimeError(
                    "pod peer lane has no apply_deltas handler"
                )
            # Off-loop: a replay batch into a device-backed storage is
            # a blocking, lock-taking apply — running it inline would
            # stall every peer's forwards (and our own probes) behind
            # the freshly recovered host's catch-up.
            applied = await asyncio.get_running_loop().run_in_executor(
                None, self.apply_cb, payload.get("deltas", [])
            )
            return json.dumps({"ok": True, "applied": int(applied)}).encode()
        if self._epoch_mismatch(payload):
            # unary (FORWARD and PINNED verdicts alike): a forward
            # stamped with a topology epoch we are not on would be
            # decided by a wrong owner — reject rerouteable instead
            return self._stale_response()
        self.served += 1
        # Cross-host decision tracing (ISSUE 12): adopt the origin's
        # request id for this task's context, so OUR flight-recorder
        # entries (batcher-recorded or lane-offered below) and spans
        # correlate with the hop's originating request.
        meta: dict = {}
        try:
            meta = dict(context.invocation_metadata() or ())
        except Exception:
            meta = {}
        rid = meta.get("x-request-id")
        if rid is not None:
            set_request_id(str(rid))
        t_decide = time.perf_counter()
        with peer_decide_span(payload["ns"], rid, meta):
            result = await self.decide_cb(
                payload["ns"],
                _decode_context(payload["ctx"]),
                int(payload["delta"]),
                bool(payload.get("load", False)),
                kind,
            )
        decide_s = time.perf_counter() - t_decide
        tap = self.flight
        if tap is not None:
            # Owner-side exemplar of a forwarded decision (ISSUE 16):
            # same request id as the origin's pod_forward entry, so one
            # bundle shows both sides of the hop.
            tap.tap(
                decide_s, "pod_forward",
                request_id=str(rid) if rid is not None else None,
                namespace=str(payload["ns"]),
                phases_ms={
                    "pod_remote_decide": round(decide_s * 1e3, 4),
                },
            )
        recorder = self.recorder
        if recorder is not None:
            flight = getattr(recorder, "flight", recorder)
            if flight.would_admit(decide_s):
                # The owner-side record of a forwarded decision: the
                # batched storages also record through the contextvar,
                # but this entry exists for EVERY storage topology.
                flight.offer(decide_s, {
                    "request_id": str(rid) if rid is not None else None,
                    "namespace": str(payload["ns"]),
                    "batch_id": None,
                    "queue_wait_ms": 0.0,
                    "phases_ms": {
                        "pod_remote_decide": round(decide_s * 1e3, 4),
                    },
                    "pod_hop": {
                        "origin": int(payload.get("from", -1)),
                        "host": self.host_id,
                    },
                })
        out: dict = {"ok": True, "decide_ns": int(decide_s * 1e9)}
        if isinstance(result, CheckResult):
            out["limited"] = bool(result.limited)
            out["name"] = result.limit_name
            out["counters"] = [
                {
                    "max": c.max_value,
                    "remaining": c.remaining,
                    "expires_in": c.expires_in,
                    "window": c.window_seconds,
                    "name": c.limit.name if c.limit is not None else None,
                }
                for c in result.counters
            ]
        return json.dumps(out).encode()

    # -- client side ---------------------------------------------------------

    def _emit(self, kind: str, **detail) -> None:
        """Typed pod event, when a log is attached (None = zero cost).
        Called with NO lane/health locks held — the log takes its own."""
        events = self.events
        if events is not None:
            events.emit(kind, **detail)

    def _redial(self, host: int) -> None:
        """Drop the cached channel so the next attempt dials fresh (lane
        loop only). A peer restarted on the same address must not keep
        failing on the stale channel's backoff state."""
        entry = self._channels.pop(host, None)
        if entry is not None:
            self.redials += 1
            self._emit("channel_redial", peer=host)
            asyncio.ensure_future(entry[0].close())

    def _dial(self, host: int):
        """A genuinely fresh channel. The local subchannel pool is the
        load-bearing option: grpc shares subchannels globally by
        target, so without it a 're-dialed' channel silently inherits
        the dead subchannel's connect-backoff state and keeps refusing
        a peer that already restarted on the same address."""
        import grpc

        channel = grpc.aio.insecure_channel(
            self.peers[host],
            options=(("grpc.use_local_subchannel_pool", 1),),
        )
        call = channel.unary_unary(
            PEER_METHOD,
            request_serializer=bytes,
            response_deserializer=bytes,
        )
        return channel, call

    async def _attempt(
        self, host: int, blob: bytes, timeout: float, fresh: bool = False,
        metadata=None,
    ) -> bytes:
        await self.faults.apply(host, timeout)
        if fresh:
            # Hedge/retry attempts dial their own channel: the point is
            # to escape whatever the cached channel is stuck on.
            channel, call = self._dial(host)
            try:
                return await self._call(host, call, blob, timeout, metadata)
            finally:
                asyncio.ensure_future(channel.close())
        entry = self._channels.get(host)
        if entry is None:
            entry = self._channels[host] = self._dial(host)
        _channel, call = entry
        return await self._call(host, call, blob, timeout, metadata)

    @staticmethod
    async def _call(
        host: int, call, blob: bytes, timeout: float, metadata=None
    ) -> bytes:
        try:
            return await call(blob, timeout=timeout, metadata=metadata)
        except asyncio.CancelledError as exc:
            # A concurrent health trip re-dialed (closed) this channel
            # under the in-flight call; grpc surfaces that as a call
            # CANCELLATION, which as a BaseException would sail past
            # every failure handler and escape to the serving plane.
            # Surface it as the connection failure it is, so the normal
            # retry/degraded handling applies.
            raise ConnectionError(
                f"peer {host} channel closed mid-call"
            ) from exc

    def _note_failure(self, host: int, exc: BaseException) -> None:
        """Health accounting + re-dial on trip (lane loop only)."""
        tripped = self.health.record_failure(
            host, deadline_miss=_is_deadline_miss(exc)
        )
        if tripped is not None:
            self._emit(
                f"peer_{tripped}", peer=host, error=f"{exc}"[:200]
            )
            self._redial(host)

    def _note_success(self, host: int) -> None:
        """Health accounting for a successful call: a transition back
        to up is a timeline event."""
        if self.health.record_success(host) is not None:
            self._emit("peer_up", peer=host)

    async def _forward_on_loop(
        self, host: int, blob: bytes, kind: str, metadata=None,
        t_submit: Optional[float] = None,
    ):
        """One forward with the lane's resilience budgeted against
        ``cfg.deadline_s``: optional hedge race, then at most one
        jittered-backoff retry for retryable kinds once the peer is
        suspect. Runs on the lane loop; returns ``(raw, queue_s)`` —
        the serving-loop -> lane-loop handoff time is the ``queue``
        phase of the hop breakdown (ISSUE 12)."""
        queue_s = (
            max(time.perf_counter() - t_submit, 0.0)
            if t_submit is not None else 0.0
        )
        cfg = self.cfg
        deadline = self._loop.time() + cfg.deadline_s
        retryable = cfg.retry and kind in RETRYABLE_KINDS

        async def one_attempt(fresh: bool = False) -> bytes:
            remaining = deadline - self._loop.time()
            if remaining <= 0:
                raise TimeoutError(
                    f"forward deadline exhausted for peer {host}"
                )
            return await self._attempt(
                host, blob, remaining, fresh=fresh, metadata=metadata
            )

        try:
            if cfg.hedge_ms > 0 and kind in RETRYABLE_KINDS:
                raw = await self._hedged(host, one_attempt, deadline)
            else:
                raw = await one_attempt()
        except Exception as exc:
            self._note_failure(host, exc)
            remaining = deadline - self._loop.time()
            backoff = (cfg.retry_backoff_ms / 1e3) * (
                0.5 + random.random()
            )
            if not (
                retryable
                and self.health.state(host) != PeerState.UP
                and remaining > backoff
            ):
                raise
            self.retries += 1
            await asyncio.sleep(backoff)
            try:
                raw = await one_attempt(fresh=True)
            except Exception as retry_exc:
                self._note_failure(host, retry_exc)
                raise
        self._note_success(host)
        return raw, queue_s

    async def _hedged(self, host: int, one_attempt, deadline) -> bytes:
        """Race a second attempt on a fresh channel when the first
        outlasts max(hedge floor, tracked peer p99) — the stall
        signature of a wedged channel, not a slow decision."""
        cfg = self.cfg
        first = asyncio.ensure_future(one_attempt())
        hedge_after = max(cfg.hedge_ms, self.peer_p99_ms()) / 1e3
        done, _pending = await asyncio.wait({first}, timeout=hedge_after)
        if first in done:
            return first.result()
        if deadline - self._loop.time() <= 0.001:
            return await first  # no budget left to hedge with
        self._emit("hedge_fired", peer=host)
        second = asyncio.ensure_future(one_attempt(fresh=True))
        pending = {first, second}
        last_exc: Optional[BaseException] = None
        while pending:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                exc = task.exception()
                if exc is not None:
                    last_exc = exc
                    continue
                for other in pending:
                    other.cancel()
                if task is second:
                    self.hedges_won += 1
                    self._emit("hedge_won", peer=host)
                else:
                    self.hedges_lost += 1
                return task.result()
        assert last_exc is not None
        raise last_exc

    async def _probe(self, host: int) -> None:
        """Ping a non-up peer (lane loop). Success hands off to the
        recovery thread so journal replay never blocks this loop."""
        if host in self._probing:
            return  # a slow probe is still in flight for this peer
        self._probing.add(host)
        self.probes += 1
        blob = json.dumps({"kind": "ping", "from": self.host_id}).encode()
        try:
            # fresh=True every probe: the cached channel's gRPC connect
            # backoff grows toward minutes on a long outage, and only a
            # genuinely fresh dial notices the instant a peer restarts
            # on the same address — recovery latency must be the probe
            # interval, not the backoff curve.
            await self._attempt(
                host, blob, self.cfg.probe_timeout_s, fresh=True
            )
        except Exception as exc:
            self._note_failure(host, exc)
            return
        finally:
            self._probing.discard(host)
        if host in self._recovering:
            return
        self._recovering.add(host)
        threading.Thread(
            target=self._run_recovery,
            args=(host,),
            name=f"pod-recover-{host}",
            daemon=True,
        ).start()

    def _run_recovery(self, host: int) -> None:
        """Recovery thread: let the frontend replay its journal to the
        recovered owner, then mark the peer up. A failed replay leaves
        the peer non-up so the next probe retries."""
        try:
            hook = self.on_peer_recovered
            ok = True if hook is None else bool(hook(host))
            if ok:
                self._note_success(host)
        except Exception as exc:
            log.warning(
                f"pod peer {host} recovery failed (stays degraded): {exc}"
            )
        finally:
            self._recovering.discard(host)

    async def _exchange_signals(self, host: int) -> None:
        """One federated-signal exchange with an up peer (lane loop,
        probe cadence — ISSUE 12). Failures are counted but deliberately
        NOT fed into peer health: health is the forwards'/probes'
        verdict, and a refused diagnostics exchange must never down a
        peer that is serving traffic fine."""
        if host in self._signal_inflight:
            return  # a slow exchange is still in flight for this peer
        provider = self.signals_provider
        if provider is None:
            return
        self._signal_inflight.add(host)
        try:
            payload = provider()
            blob = json.dumps({
                "kind": "signals",
                "from": self.host_id,
                "signals": payload,
            }).encode()
            raw = await self._attempt(
                host, blob, self.cfg.probe_timeout_s
            )
            self.signal_exchanges += 1
            hook = self.on_peer_signals
            theirs = json.loads(raw.decode()).get("signals") or {}
            if hook is not None and theirs:
                hook(host, theirs)
        except Exception:
            self.signal_exchange_failures += 1
        finally:
            self._signal_inflight.discard(host)

    def replay_deltas(
        self, host: int, deltas: List[dict],
        timeout: float = FORWARD_TIMEOUT_SECONDS,
    ) -> int:
        """Blocking journal replay to a recovered owner — recovery
        thread only, NEVER the serving path. Raises on peer failure so
        the caller's journal restore fires."""
        blob = json.dumps({
            "kind": "apply_deltas",
            "deltas": deltas,
            "from": self.host_id,
        }).encode()
        fut = asyncio.run_coroutine_threadsafe(
            self._attempt(host, blob, timeout), self._loop
        )
        raw = fut.result(timeout + 1.0)
        return int(json.loads(raw.decode()).get("applied", 0))

    async def forward(
        self,
        host: int,
        namespace: str,
        ctx: Context,
        delta: int,
        load: bool,
        kind: str = "check_and_update",
    ) -> dict:
        """Forward one decision to its owner host (callable from any
        serving event loop; the channel work runs on the lane loop).
        Raises on peer failure after counting it — the caller maps that
        to its shed/unavailable semantics."""
        if host not in self.peers:
            self.errors += 1
            raise RuntimeError(f"no peer lane for pod host {host}")
        # Cross-host decision tracing (ISSUE 12): the originating
        # request id and (when an exporter is live) the W3C trace
        # context ride the hop as gRPC metadata, so the owner's
        # flight-recorder entries and spans correlate back to us.
        request_id = _wire_request_id(current_request_id())
        t0 = time.perf_counter()
        wire = {
            "ns": str(namespace),
            "ctx": _encode_context(ctx),
            "delta": int(delta),
            "load": bool(load),
            "kind": kind,
            "from": self.host_id,
        }
        provider = self.epoch_provider
        if provider is not None:
            # resize armed: stamp the topology epoch the routing
            # verdict was computed under (one int per payload)
            wire["tepoch"] = int(provider())
        blob = json.dumps(wire).encode()
        serialize_s = time.perf_counter() - t0
        metadata = None
        pairs = hop_trace_metadata()
        if request_id is not None:
            pairs.append(("x-request-id", request_id))
        if pairs:
            metadata = tuple(pairs)
        fut = asyncio.run_coroutine_threadsafe(
            self._forward_on_loop(
                host, blob, kind, metadata=metadata,
                t_submit=time.perf_counter(),
            ),
            self._loop,
        )
        try:
            raw, queue_s = await asyncio.wrap_future(fut)
        except Exception:
            self.errors += 1
            raise
        self.forwards += 1
        total_s = time.perf_counter() - t0
        with self._latency_lock:
            self._latencies_ms.append(total_s * 1e3)
        resp = json.loads(raw.decode())
        if resp.get(STALE_EPOCH):
            # adopt the rejection's (possibly newer) topology BEFORE
            # the caller re-plans, so the re-plan routes by it
            hook = self.on_stale
            if hook is not None:
                try:
                    hook(resp)
                except Exception:
                    pass
            return resp
        hook = self.on_hop
        if hook is not None:
            # The per-hop breakdown: the owner reports its decide time,
            # wire is the unaccounted remainder (channel, network,
            # retries/hedges, response parse).
            remote_s = max(float(resp.get("decide_ns", 0)) / 1e9, 0.0)
            hook(host, request_id, namespace, total_s, {
                "queue": queue_s,
                "serialize": serialize_s,
                "wire": max(
                    total_s - queue_s - serialize_s - remote_s, 0.0
                ),
                "remote_decide": remote_s,
            })
        return resp

    async def forward_bulk(
        self, host: int, blobs: List[bytes]
    ) -> List[Optional[bytes]]:
        """One bulk forward of foreign-owned raw request blobs to their
        owner host (ISSUE 13): the whole flush group rides ONE lane RPC
        with the lane's full resilience (retry while suspect, hedging,
        health accounting). Returns one response payload per blob; None
        rows could not be decided terminally by the owner — the caller
        falls back to its per-request hop. Raises on peer failure after
        counting it. The per-hop breakdown (PR 12) is recorded exactly
        like a single forward's, under the ``_bulk`` namespace."""
        if host not in self.peers:
            self.errors += 1
            raise RuntimeError(f"no peer lane for pod host {host}")
        request_id = _wire_request_id(current_request_id())
        t0 = time.perf_counter()
        wire = {
            "kind": "bulk_decide",
            "from": self.host_id,
            "blobs": [base64.b64encode(b).decode() for b in blobs],
        }
        provider = self.epoch_provider
        if provider is not None:
            wire["tepoch"] = int(provider())
        blob = json.dumps(wire).encode()
        serialize_s = time.perf_counter() - t0
        metadata = None
        pairs = hop_trace_metadata()
        if request_id is not None:
            pairs.append(("x-request-id", request_id))
        if pairs:
            metadata = tuple(pairs)
        fut = asyncio.run_coroutine_threadsafe(
            self._forward_on_loop(
                host, blob, "bulk_decide", metadata=metadata,
                t_submit=time.perf_counter(),
            ),
            self._loop,
        )
        try:
            raw, queue_s = await asyncio.wrap_future(fut)
        except Exception:
            self.errors += 1
            raise
        self.bulk_forwards += 1
        self.bulk_forward_rows += len(blobs)
        total_s = time.perf_counter() - t0
        with self._latency_lock:
            self._latencies_ms.append(total_s * 1e3)
        resp = json.loads(raw.decode())
        if resp.get(STALE_EPOCH):
            # the whole batch was routed by a dead topology: adopt the
            # newer one, answer all-None — every row falls back to its
            # per-request path, which re-plans under the new epoch
            hook = self.on_stale
            if hook is not None:
                try:
                    hook(resp)
                except Exception:
                    pass
            return [None] * len(blobs)
        hook = self.on_hop
        if hook is not None:
            remote_s = max(float(resp.get("decide_ns", 0)) / 1e9, 0.0)
            hook(host, request_id, "_bulk", total_s, {
                "queue": queue_s,
                "serialize": serialize_s,
                "wire": max(
                    total_s - queue_s - serialize_s - remote_s, 0.0
                ),
                "remote_decide": remote_s,
            })
        return [
            None if p is None else base64.b64decode(p)
            for p in resp.get("payloads", ())
        ]

    # -- telemetry -----------------------------------------------------------

    def peer_p99_ms(self) -> float:
        with self._latency_lock:
            lat = sorted(self._latencies_ms)
        if not lat:
            return 0.0
        return lat[min(int(0.99 * len(lat)), len(lat) - 1)]

    def stats(self) -> dict:
        return {
            "pod_peer_forwards": self.forwards,
            "pod_peer_served": self.served,
            "pod_peer_errors": self.errors,
            "pod_bulk_forward_batches": self.bulk_forwards,
            "pod_bulk_forward_rows": self.bulk_forward_rows,
            "pod_bulk_served_rows": self.bulk_served_rows,
            "pod_peer_p99_ms": round(self.peer_p99_ms(), 3),
            # owner-side wrong-epoch rejections (ISSUE 15; family owned
            # by server/resize.py — the value lives on the lane's gate)
            "pod_resize_stale_rejects": self.stale_rejects,
            "peer_health_state": self.health.states(),
            "peer_health_retries": self.retries,
            "peer_health_hedges_won": self.hedges_won,
            "peer_health_hedges_lost": self.hedges_lost,
            "peer_health_redials": self.redials,
            "peer_health_probes": self.probes,
            # client-side exchange outcomes (the aggregator owns the
            # pod_signal_exchanges family — columns actually ingested)
            "pod_signal_sent": self.signal_exchanges,
            "pod_signal_send_failures": self.signal_exchange_failures,
        }


class _OwnerGuard:
    """Per-owner failover state: the admission plane's breaker core
    gating a local exact stand-in (FailoverStore) whose journal replays
    to the owner on recovery. The breaker's stall watch is disarmed —
    peer failures arrive as recorded exceptions, not stalled batches."""

    def __init__(self, owner: int, cfg: PodResilience):
        self.owner = owner
        self.breaker = CircuitBreaker(
            failure_threshold=cfg.breaker_failures,
            stall_timeout=1e9,
            reset_timeout=cfg.breaker_reset_s,
            warmup_stall_timeout=1e9,
        )
        self.store = FailoverStore(cache_size=cfg.journal_cache)
        self.degraded_decisions = 0
        self.reconciles = 0
        self.replayed_deltas = 0
        self.reconcile_seconds = 0.0
        # wall clock of the current degraded window's first stand-in
        # decision (None = not degraded) — the degraded_enter/exit
        # event pair brackets it on the pod timeline (ISSUE 12).
        # Guarded: degraded decisions race in from EVERY serving loop
        # while the recovery thread clears, and an unsynchronized
        # check-then-set would double degraded_enter (or strand an
        # exit inside a re-opened window) on a flapping owner.
        self.degraded_since: Optional[float] = None
        self._degraded_lock = threading.Lock()


class _PeerDeltaSink:
    """apply_deltas adapter over the peer lane, so FailoverStore's
    reconcile_into (journal restore on failure, oracle clear on
    success) replays to a REMOTE owner exactly as the admission plane
    replays to the local device table.

    Chunked: a long partition can journal far more counters than one
    gRPC message survives (the lane server runs the default 4MB
    receive cap), so the replay ships bounded batches. The sink exposes
    ``apply_deltas_acked`` so FailoverStore's reconcile tracks the
    acknowledged-chunk high-water mark: a failure mid-replay restores
    only the UN-acked tail, and a re-driven reconcile (a mid-migration
    peer death, ISSUE 15 satellite) never double-applies the prefix
    the owner already counted."""

    CHUNK = 1000

    def __init__(self, lane: PeerLane, owner: int):
        self._lane = lane
        self._owner = owner

    def apply_deltas_acked(self, items, ack) -> None:
        deltas = [
            _counter_to_wire(counter, delta) for counter, delta in items
        ]
        for start in range(0, len(deltas), self.CHUNK):
            chunk = deltas[start:start + self.CHUNK]
            self._lane.replay_deltas(self._owner, chunk)
            ack(start + len(chunk))

    def apply_deltas(self, items) -> None:
        self.apply_deltas_acked(items, lambda _n: None)


class PodFrontend:
    """Shard-aware routed frontend over a limiter: decide locally when
    this host owns every counter the request touches, else one
    peer-lane hop to the owner. Used by RlsService/http_api exactly
    like the limiter it wraps (attribute delegation).

    With ``resilience.degraded`` on, a failed forward is never the
    request's failure: the owner's traffic fails over to a per-owner
    exact stand-in behind a circuit breaker, every admitted delta is
    journaled, and the lane's background probe replays the journal to
    the owner once it answers again (module docstring)."""

    #: RlsService awaits check/update calls when this is set even
    #: though we are not an AsyncRateLimiter instance
    is_async_limiter = True

    def __init__(
        self,
        limiter,
        router: PodRouter,
        lane: PeerLane,
        global_namespaces=(),
        resilience: Optional[PodResilience] = None,
        events_capacity: int = 512,
    ):
        self._limiter = limiter
        self.router = router
        self.lane = lane
        self._global_ns = {str(ns) for ns in global_namespaces}
        self._inner_async = isinstance(limiter, AsyncRateLimiter)
        self._resilience = resilience or lane.cfg
        self._guards: Dict[int, _OwnerGuard] = {}
        #: native pipeline with the shard-aware hot lane attached
        #: (attach_pipeline, ISSUE 13); None = routed compiled plane
        self.pipeline = None
        #: lockstep global-mesh psum lane (parallel/mesh.py
        #: PodPsumLane, ISSUE 13); eligible global namespaces decide
        #: LOCALLY through it instead of funneling to a pin host
        self.psum_lane = None
        #: PodResizeCoordinator (server/resize.py, ISSUE 15); None =
        #: PR 14 behavior byte-identical (no epoch stamping, no gate)
        self.resize = None
        #: server/standby.WarmStandby (ISSUE 18); None = not a warm
        #: standby (the default — join callbacks stay unarmed)
        self.standby = None
        #: forwards answered stale_epoch that re-planned in-band
        self.stale_replans = 0
        #: the last applied limits generation — the resize coordinator
        #: enumerates migratable counters from it
        self._last_limits: List = []
        # Pod observability plane (ISSUE 12): the typed event timeline,
        # the per-hop breakdown recorder and the federated signal
        # aggregator — always on (bounded rings, off the decision
        # path); the lane emits through the hooks below.
        self.events = PodEventLog(
            host_id=lane.host_id, capacity=events_capacity
        )
        #: flight.FlightRecorder (ISSUE 16): the always-on exemplar
        #: rings; None = detached (attach_flight_recorder arms it)
        self.flight = None
        self.hops = PodHopRecorder(host_id=lane.host_id)
        self.aggregator = PodSignalAggregator(host_id=lane.host_id)
        self.aggregator.local_fields = self.pod_signal_fields
        lane.events = self.events
        lane.on_hop = self._record_hop
        lane.signals_provider = self.aggregator.local_payload
        lane.on_peer_signals = self.aggregator.ingest
        if self._resilience.degraded:
            self._guards = {
                owner: _OwnerGuard(owner, self._resilience)
                for owner in lane.peers
            }
            lane.on_peer_recovered = self._peer_recovered
            lane.probe_needed = self._needs_recovery
            for owner, guard in self._guards.items():
                guard.breaker.listeners.append(
                    self._breaker_listener(owner)
                )
        lane.decide_cb = self._decide_for_peer
        # The owner side of a journal replay is unconditional: a
        # recovered host must accept its peers' journals even when its
        # own degraded mode is off.
        lane.apply_cb = self._apply_from_peer

    def __getattr__(self, name):
        return getattr(self._limiter, name)

    # -- configuration -------------------------------------------------------

    async def configure_with(self, limits) -> None:
        limits = list(limits)
        # The psum lane claims eligible global namespaces FIRST: the
        # router must not pin what the lane decides locally everywhere
        # (routed-share -> 1 is the whole point, ISSUE 13).
        pinned_global = self._global_ns
        if self.psum_lane is not None:
            served = self.psum_lane.configure(limits, self._global_ns)
            pinned_global = self._global_ns - served
        self._last_limits = limits
        self.router.configure(limits, pinned_global)
        self.events.emit(
            "routing_epoch", epoch=self.router.epoch, limits=len(limits)
        )
        res = self._limiter.configure_with(limits)
        if inspect.isawaitable(res):
            await res

    # -- pod fast path (ISSUE 13) --------------------------------------------

    def attach_pipeline(self, pipeline) -> None:
        """Wire the native pipeline into the pod: the C hot lane learns
        the topology + per-plan owner stamps (``attach_pod``) and this
        lane's ``bulk_decide`` handler decides forwarded blob batches
        on the local plane — the zero-Python path now serves pod mode."""
        pipeline.attach_pod(self)
        self.pipeline = pipeline
        self.lane.bulk_cb = pipeline.decide_blobs_for_peer

    def attach_psum_lane(self, lane) -> None:
        """Attach the lockstep global-mesh psum lane: global-namespace
        limits it can serve stop pinning to one host — every ingress
        decides them locally against the pod-wide psum aggregate."""
        self.psum_lane = lane

    def attach_psum_transport(self, transport) -> None:
        """Per-host meshes (ISSUE 18): wire a
        parallel.PeerPsumTransport into this lane — peers' published
        partials arrive through the ``psum_share`` kind, and our own
        publishes ride the lane's admin_call from the psum pacer
        thread (psum_share_sender below). The psum lane then needs no
        `jax.distributed` coordination client at all."""
        self.lane.psum_share_cb = transport.receive

    # -- fast join: shipped plan caches (ISSUE 18) ---------------------------

    def _limits_fingerprint(self) -> str:
        """A stable digest of the applied limits generation: the
        plan-seed ship stamps it so a seed derived under one limits
        file never lands on a joiner that configured a different one
        (the cross-process half of the stale-epoch contract — epoch
        counters themselves are process-local)."""
        from ..tpu.plan_cache import _limit_identity_to_wire

        idents = sorted(
            json.dumps(_limit_identity_to_wire(lim), sort_keys=True)
            for lim in self._last_limits
        )
        return hashlib.sha256(
            "\n".join(idents).encode()
        ).hexdigest()[:16]

    def plan_seed_export(self, max_entries: int = 4096) -> dict:
        """This host's decision-plan cache as one shippable seed
        payload (the coordinator sends it to a joiner over the
        ``plan_seed`` lane kind). Kernel plans ship counter IDENTITY,
        not slots — device slots are host-local; the importer
        re-resolves each hit against its own table."""
        cache = (
            getattr(self.pipeline, "plan_cache", None)
            if self.pipeline is not None else None
        )
        if cache is None:
            return {"entries": [], "limits_fp": self._limits_fingerprint()}
        table = self.pipeline.storage._table

        def counter_of_slot(slot):
            entry = table.info.get(slot)
            return entry[1] if entry is not None else None

        return {
            "entries": cache.export_seed(
                counter_of_slot, max_entries=max_entries
            ),
            "limits_fp": self._limits_fingerprint(),
        }

    def plan_seed_import(self, payload: dict) -> dict:
        """The joiner side of a shipped seed: rebuild every entry
        against OUR slot table and ride the cache's put() so a limits
        reload racing the ship discards in flight (epoch moved).
        A seed stamped with a different limits fingerprint is
        discarded whole — it was derived under limits we never
        applied."""
        cache = (
            getattr(self.pipeline, "plan_cache", None)
            if self.pipeline is not None else None
        )
        if cache is None:
            return {"ok": False, "error": "no plan cache attached"}
        fp = payload.get("limits_fp")
        if fp is not None and fp != self._limits_fingerprint():
            self.events.emit("plan_seeded", seeded=0, stale=True)
            return {"ok": True, "seeded": 0, "stale_limits": True}
        storage = self.pipeline.storage

        def slot_of_counter(counter):
            with storage._lock:
                slot, _fresh = storage._slot_for(counter, create=True)
            return slot

        entries = payload.get("entries") or ()
        seeded = cache.import_seed(
            entries, slot_of_counter, epoch=cache.epoch
        )
        self.events.emit(
            "plan_seeded", entries=len(entries), seeded=seeded
        )
        return {"ok": True, "seeded": seeded}

    # -- elastic pod (ISSUE 15) ----------------------------------------------

    def attach_resize(self, coordinator) -> None:
        """Arm the elastic-membership plane: forwards stamp the
        topology epoch, the owner-side gate rejects wrong-epoch
        forwards rerouteable, and the lane's migrate/resize_admin
        kinds route to the coordinator. Without this call the wire
        format and serve path are byte-identical to PR 14."""
        self.resize = coordinator
        self.lane.epoch_provider = (
            lambda: self.router.topology_epoch
        )
        self.lane.stale_info_provider = coordinator.stale_info
        self.lane.migrate_cb = coordinator.handle_migrate
        self.lane.resize_cb = coordinator.handle_admin
        self.lane.on_stale = coordinator.adopt_remote

    def ensure_guards(self) -> None:
        """Create degraded-owner guards for peers that joined after
        construction (live membership change): every forwardable owner
        keeps the failover safety net."""
        if not self._resilience.degraded:
            return
        for owner in self.lane.peers:
            if owner not in self._guards:
                guard = _OwnerGuard(owner, self._resilience)
                guard.breaker.listeners.append(
                    self._breaker_listener(owner)
                )
                self._guards[owner] = guard

    async def _stale_replan(
        self, namespace, ctx, delta, load, kind
    ):
        """A forward was rejected stale_epoch: the topology moved under
        the request. Re-plan under the (possibly just-adopted) current
        topology, bounded: the commit broadcast lands within
        milliseconds, so a couple of spaced re-plans cover both the
        we-are-behind and the owner-is-behind races; the degraded
        stand-in is the terminal fallback — a membership change must
        never fail a request that PR 11 machinery can answer."""
        self.stale_replans += 1
        owner = None
        counters: List[Counter] = []
        for attempt in range(3):
            verdict, owner, counters = self._route(namespace, ctx)
            if verdict == LOCAL:
                if kind == "is_rate_limited":
                    return await self._local_is_limited(
                        namespace, ctx, delta, counters
                    )
                if kind == "update_counters":
                    await self._local_update(
                        namespace, ctx, delta, counters
                    )
                    return None
                return await self._local_check(
                    namespace, ctx, delta, load, counters
                )
            guard = self._guards.get(owner)
            if guard is not None and guard.breaker.is_open():
                return self._degraded_decide(
                    guard, counters, delta, load, kind
                )
            try:
                resp = await self.lane.forward(
                    owner, namespace, ctx, delta, load, kind=kind
                )
            except Exception as exc:
                err = StorageError(
                    f"pod peer host {owner} unavailable: {exc}"
                )
                if guard is not None:
                    guard.breaker.record_failure(err)
                    return self._degraded_decide(
                        guard, counters, delta, load, kind
                    )
                raise err from exc
            if isinstance(resp, dict) and resp.get(STALE_EPOCH):
                # either side may still be mid-commit: give the
                # broadcast a moment, then re-plan again
                await asyncio.sleep(0.02 * (attempt + 1))
                continue
            if guard is not None:
                guard.breaker.record_success()
            if kind == "update_counters":
                return None
            return self._adopt(resp)
        guard = self._guards.get(owner)
        if guard is not None:
            return self._degraded_decide(guard, counters, delta, load, kind)
        raise StorageError(
            f"pod topology epoch disagreement with host {owner} "
            "(resize in flight, no degraded fallback)"
        )

    def resize_debug(self) -> dict:
        """``GET /debug/pod/resize`` + the ``pod_resize`` /debug/stats
        section: the transition state machine's live view."""
        if self.resize is None:
            return {"armed": False}
        out = self.resize.status()
        out["armed"] = True
        return out

    def pod_resize_admin(self, hosts: int, peers=None) -> dict:
        """The admin surface behind ``POST /debug/pod/resize``
        (blocking — the HTTP handler runs it in an executor)."""
        if self.resize is None:
            raise StorageError("pod resize not armed (--pod-resize off)")
        return self.resize.resize(int(hosts), peers=peers)

    def standby_debug(self) -> dict:
        """``GET /debug/pod/standby`` + the ``standby`` /debug/stats
        section (ISSUE 18): warm-up state and join readiness."""
        if self.standby is None:
            return {"armed": False}
        out = self.standby.status()
        out["armed"] = True
        return out

    def pod_join_admin(
        self, address: str, replace=None, seed_plans: bool = True
    ) -> dict:
        """The admin surface behind ``POST /debug/pod/join``: promote
        the warm standby at ``address`` into the pod (blocking — the
        HTTP handler runs it in an executor)."""
        if self.resize is None:
            raise StorageError("pod resize not armed (--pod-resize off)")
        return self.resize.join_host(
            address, replace=replace, seed_plans=seed_plans
        )

    async def forward_bulk(
        self, owner: int, blobs: List[bytes]
    ) -> List[Optional[bytes]]:
        """One bulk forward with the degraded-owner machinery applied
        at BATCH granularity: an open breaker refuses the hop outright
        (the pipeline falls back per-row into the frontend's stand-in
        path), and batch failures feed the same breaker single forwards
        feed."""
        guard = self._guards.get(owner)
        if guard is not None and guard.breaker.is_open():
            raise StorageError(
                f"pod peer host {owner} degraded (breaker open)"
            )
        try:
            payloads = await self.lane.forward_bulk(owner, blobs)
        except Exception as exc:
            if guard is not None:
                guard.breaker.record_failure(exc)
            raise
        if guard is not None:
            guard.breaker.record_success()
        return payloads

    def forward_bulk_submit(self, owner: int, blobs: List[bytes]):
        """Submit a bulk hop WITHOUT blocking: returns the
        concurrent.futures handle (or ``None`` when the lane loop is
        down). The engine path submits every owner's hop first and only
        then collects, so a chunk spanning p-1 foreign owners pays
        max-of-RPC-latencies, not sum."""
        lane = self.lane
        if lane._loop is None:
            return None
        return asyncio.run_coroutine_threadsafe(
            self.forward_bulk(owner, blobs), lane._loop
        )

    def forward_bulk_collect(self, fut, n: int) -> List[Optional[bytes]]:
        """Resolve a ``forward_bulk_submit`` handle; failures answer
        all-None so every row falls back to its per-request path
        instead of failing the chunk."""
        if fut is None:
            return [None] * n
        try:
            return fut.result(self.lane.cfg.deadline_s + 1.0)
        except Exception:
            return [None] * n

    def routing_debug(self) -> dict:
        """``GET /debug/pod/routing``: the ownership map an upstream LB
        can learn (topology, shard blocks, pinned namespaces, epoch),
        plus what the pod fast path is serving with."""
        out = self.router.ownership_map()
        out["peers"] = {
            str(h): addr for h, addr in self.lane.peers.items()
        }
        out["native_hot_lane"] = self.pipeline is not None
        out["psum_lane_namespaces"] = (
            sorted(self.psum_lane.namespaces)
            if self.psum_lane is not None else []
        )
        return out

    # -- pod observability plane (ISSUE 12) ----------------------------------

    def _breaker_listener(self, owner: int):
        """Per-owner breaker transition -> typed timeline event (the
        breaker calls listeners OUTSIDE its lock)."""
        kinds = {
            BreakerState.OPEN: "breaker_open",
            BreakerState.HALF_OPEN: "breaker_half_open",
            BreakerState.CLOSED: "breaker_closed",
        }

        def on_transition(state: str) -> None:
            kind = kinds.get(state)
            if kind is not None:
                self.events.emit(kind, owner=owner)

        return on_transition

    def _record_hop(
        self, host, request_id, namespace, total_s, phases_s
    ) -> None:
        self.hops.record(request_id, host, namespace, total_s, phases_s)

    def attach_flight(self, recorder) -> None:
        """Wire the process flight recorder into BOTH hop directions:
        the origin-side per-hop breakdown entries and the owner-side
        forwarded-decide entries (every storage topology, not just the
        batched ones that record through the contextvar)."""
        self.hops.attach_flight(recorder)
        self.lane.recorder = recorder

    def attach_flight_recorder(self, flight) -> None:
        """Arm the ISSUE 16 flight recorder on every pod lane: origin-
        side forwards (hop tap), owner-side decides and the ``flight``
        ring-contribution kind (lane), the degraded stand-in path, and
        the topology epoch stamped into every sampled exemplar."""
        self.flight = flight
        self.lane.flight = flight
        self.hops.tap = flight
        flight.epoch_provider = lambda: self.router.topology_epoch

    def attach_signal_bus(self, bus) -> None:
        """Join the local ControlSignals bus into the federated view
        (and the pod fields into the bus — both directions)."""
        self.aggregator.local_signals = bus.snapshot
        attach = getattr(bus, "attach_pod", None)
        if callable(attach):
            attach(self)

    def pod_signal_fields(self) -> dict:
        """The ControlSignals pod tail (ISSUE 12): this host's routed
        share, peer health counts, and degraded share — cheap reads of
        existing counters, safe from any thread."""
        routed = self.router.stats()
        total = (
            routed["pod_routed_local"]
            + routed["pod_routed_forwarded"]
            + routed["pod_routed_pinned"]
        )
        states = self.lane.health.states()
        degraded = sum(
            guard.degraded_decisions for guard in self._guards.values()
        )
        gauge_counts = {0: 0, 1: 0, 2: 0}
        for state in states.values():
            gauge_counts[state] = gauge_counts.get(state, 0) + 1
        return {
            "pod_routed_share": round(
                routed["pod_routed_local"] / total, 6
            ) if total else 0.0,
            "peers_up": gauge_counts[0],
            "peers_suspect": gauge_counts[1],
            "peers_down": gauge_counts[2],
            "pod_degraded_share": round(
                degraded / total, 6
            ) if total else 0.0,
            # elastic pod (ISSUE 15): hosts mid-transition sum across
            # the federated view — a stuck resize is visible pod-wide
            "pod_resize_active": (
                1 if self.resize is not None and self.resize.active
                else 0
            ),
            "tepoch": self.router.topology_epoch,
        }

    def pod_debug(self) -> dict:
        """``GET /debug/pod``: per-host signal columns + rollups, plus
        this host's hop breakdown summary."""
        out = self.aggregator.pod_debug()
        out["hops"] = self.hops.hop_debug()
        return out

    def events_debug(self, n=None, kind=None) -> dict:
        """``GET /debug/events``: the typed pod event timeline."""
        return self.events.events_debug(n=n, kind=kind)

    # -- routing helpers -----------------------------------------------------

    def _route(self, namespace, ctx) -> Tuple[str, int, List[Counter]]:
        # Matching runs ONCE per decision (ISSUE 13): the counters
        # resolved here feed the wrapped limiter's ``counters=`` entry
        # point on the local path, the degraded stand-in, and the psum
        # lane — no path re-matches what the router already matched.
        counters = _counters_that_apply(
            self._limiter.storage, Namespace.of(namespace), ctx
        )
        keys = [counter_key(c) for c in counters]
        verdict, owner = self.router.plan(str(namespace), keys)
        return verdict, owner, counters

    def _plan(self, namespace, ctx) -> Tuple[str, int]:
        verdict, owner, _counters = self._route(namespace, ctx)
        return verdict, owner

    async def _local_check(
        self, namespace, ctx, delta, load, counters=None
    ) -> CheckResult:
        if self._inner_async:
            return await self._limiter.check_rate_limited_and_update(
                namespace, ctx, delta, load, counters=counters
            )
        return self._limiter.check_rate_limited_and_update(
            namespace, ctx, delta, load, counters=counters
        )

    async def _local_is_limited(
        self, namespace, ctx, delta, counters=None
    ) -> CheckResult:
        if self._inner_async:
            return await self._limiter.is_rate_limited(
                namespace, ctx, delta, counters=counters
            )
        return self._limiter.is_rate_limited(
            namespace, ctx, delta, counters=counters
        )

    async def _local_update(
        self, namespace, ctx, delta, counters=None
    ) -> None:
        if self._inner_async:
            await self._limiter.update_counters(
                namespace, ctx, delta, counters=counters
            )
        else:
            self._limiter.update_counters(
                namespace, ctx, delta, counters=counters
            )

    async def _decide_for_peer(
        self, namespace, ctx, delta, load, kind
    ) -> Optional[CheckResult]:
        """Owner-side handler of a forwarded decision: we own it, so it
        runs the LOCAL path directly (no re-routing — a forward is
        always terminal, one hop by construction). Matching runs once,
        here, and flows into the limiter's precomputed-counters entry
        point."""
        rz = self.resize
        if rz is not None and rz._join_adopted_at is not None:
            # a just-promoted joiner's first answered decision (ISSUE
            # 18): stamp time-to-first-decision and leave a join-lane
            # exemplar in the flight ring. Self-disarming — one
            # attribute read per forward once stamped.
            t0 = time.perf_counter()
            try:
                return await self._decide_for_peer_inner(
                    namespace, ctx, delta, load, kind
                )
            finally:
                rz.note_first_decision()
                if self.flight is not None:
                    self.flight.tap(
                        time.perf_counter() - t0, "join",
                        request_id=current_request_id(),
                        namespace=namespace,
                    )
        return await self._decide_for_peer_inner(
            namespace, ctx, delta, load, kind
        )

    async def _decide_for_peer_inner(
        self, namespace, ctx, delta, load, kind
    ) -> Optional[CheckResult]:
        counters = _counters_that_apply(
            self._limiter.storage, Namespace.of(namespace), ctx
        )
        if kind == "is_rate_limited":
            return await self._local_is_limited(
                namespace, ctx, delta, counters
            )
        if kind == "update_counters":
            await self._local_update(namespace, ctx, delta, counters)
            return None
        return await self._local_check(namespace, ctx, delta, load, counters)

    def _apply_from_peer(self, deltas: List[dict]) -> int:
        """Owner-side journal replay: a peer that failed over while we
        were down hands us the deltas it admitted on our behalf; they
        land through the storage's apply_deltas contract (the same lane
        the write-behind authority role uses)."""
        items = [_counter_from_wire(blob) for blob in deltas]
        if not items:
            return 0
        storage = self._limiter.storage
        storage = getattr(storage, "counters", storage)
        storage.apply_deltas(items)
        return len(items)

    @staticmethod
    def _adopt(resp: dict) -> CheckResult:
        """A forwarded decision's CheckResult, with owner-loaded counter
        headers rebuilt as lightweight stand-ins."""
        counters = []
        for c in resp.get("counters", ()):
            counters.append(_ForwardedCounter(
                c.get("max"), c.get("remaining"), c.get("expires_in"),
                c.get("window"), c.get("name"),
            ))
        return CheckResult(
            bool(resp.get("limited", False)), counters, resp.get("name")
        )

    # -- degraded-owner failover ---------------------------------------------

    def _degraded_decide(
        self, guard: _OwnerGuard, counters: List[Counter],
        delta: int, load: bool, kind: str,
    ) -> Optional[CheckResult]:
        """Decide against the owner's local stand-in (exact oracle +
        delta journal). Mirrors RateLimiter's storage-to-CheckResult
        shape so serving planes can't tell a degraded answer apart."""
        tap = getattr(self, "flight", None)
        if tap is None:
            return self._degraded_decide_inner(
                guard, counters, delta, load, kind
            )
        # ISSUE 16: degraded-lane exemplars — the failover window is
        # exactly what an incident bundle needs to show.
        t0 = time.perf_counter()
        try:
            return self._degraded_decide_inner(
                guard, counters, delta, load, kind
            )
        finally:
            namespace = None
            if counters:
                limit = getattr(counters[0], "limit", None)
                namespace = getattr(limit, "namespace", None)
            tap.tap(
                time.perf_counter() - t0, "degraded",
                request_id=current_request_id(),
                namespace=namespace,
                phases_ms=None,
            )

    def _degraded_decide_inner(
        self, guard: _OwnerGuard, counters: List[Counter],
        delta: int, load: bool, kind: str,
    ) -> Optional[CheckResult]:
        entered = False
        with guard._degraded_lock:
            if guard.degraded_since is None:
                guard.degraded_since = time.time()
                entered = True
        if entered:  # emit OUTSIDE the lock (lock-order hygiene)
            self.events.emit("degraded_enter", owner=guard.owner)
        guard.degraded_decisions += 1
        if kind == "is_rate_limited":
            for counter in counters:
                if not guard.store.is_within_limits(counter, delta):
                    return CheckResult(True, [], counter.limit.name)
            return CheckResult(False, [], None)
        if kind == "update_counters":
            for counter in counters:
                guard.store.update_counter(counter, delta)
            return None
        if not counters:
            return CheckResult(False, [], None)
        auth = guard.store.check_and_update(counters, delta, load)
        loaded = counters if load else []
        if auth.limited:
            return CheckResult(True, loaded, auth.limit_name)
        return CheckResult(False, loaded, None)

    def _needs_recovery(self, owner: int) -> bool:
        """Probe-loop gate beyond peer health: a sub-threshold failure
        journals a delta while the peer stays (or comes back) UP, and a
        breaker can open without downing the peer — either way probes
        must keep firing until the journal drains and the breaker
        closes."""
        guard = self._guards.get(owner)
        if guard is None:
            return False
        return (
            guard.breaker.state != BreakerState.CLOSED
            or guard.store.journal_size() > 0
        )

    def _peer_recovered(self, owner: int) -> bool:
        """Recovery-thread hook: replay the owner's journal through the
        lane into its apply_deltas, drain the stand-in, close the
        breaker. Degraded decisions racing the replay land in a fresh
        journal, so the post-close drain below empties it — zero
        admitted deltas are lost across the partition window."""
        guard = self._guards.get(owner)
        if guard is None:
            return True
        sink = _PeerDeltaSink(self.lane, owner)
        t0 = time.perf_counter()
        self.events.emit(
            "journal_replay_begin", owner=owner,
            journal=guard.store.journal_size(),
        )
        try:
            replayed = guard.store.reconcile_into(sink)
            # Requests that went degraded between the drain above and
            # the breaker closing journal into a fresh journal; bounded
            # re-drains chase the tail down to empty.
            for _ in range(4):
                if guard.store.journal_size() == 0:
                    break
                replayed += guard.store.reconcile_into(sink)
        except Exception as exc:
            guard.reconcile_seconds += time.perf_counter() - t0
            self.events.emit(
                "journal_replay_end", owner=owner, ok=False,
                replayed=0, error=f"{exc}"[:200],
            )
            log.warning(
                f"pod host {owner}: journal replay failed, staying "
                f"degraded: {exc}"
            )
            return False
        guard.breaker.probe_succeeded()
        if guard.store.journal_size():
            try:
                replayed += guard.store.reconcile_into(sink)
            except Exception:
                pass  # residue replays on the next recovery
        guard.reconcile_seconds += time.perf_counter() - t0
        guard.reconciles += 1
        guard.replayed_deltas += replayed
        self.events.emit(
            "journal_replay_end", owner=owner, ok=True, replayed=replayed
        )
        with guard._degraded_lock:
            since, guard.degraded_since = guard.degraded_since, None
        if since is not None:
            self.events.emit(
                "degraded_exit", owner=owner,
                degraded_s=round(time.time() - since, 6),
                decisions=guard.degraded_decisions,
            )
        log.info(
            f"pod host {owner} recovered: replayed {replayed} journaled "
            "deltas, routing restored"
        )
        return True

    async def _remote(
        self, owner, namespace, ctx, counters, delta, load, kind
    ) -> Optional[CheckResult]:
        """One peer hop, with failures mapped to StorageError: the
        serving planes (rls.py aborts UNAVAILABLE, http_api answers
        500) already give StorageError the unavailable semantics a
        dead owner host deserves — a raw AioRpcError would surface as
        an unhandled UNKNOWN instead. With degraded mode on, the
        failure instead feeds the owner's breaker and the decision
        fails over to the local stand-in — the request never sees the
        dead peer at all."""
        guard = self._guards.get(owner)
        if guard is not None and guard.breaker.is_open():
            return self._degraded_decide(guard, counters, delta, load, kind)
        try:
            resp = await self.lane.forward(
                owner, namespace, ctx, delta, load, kind=kind
            )
        except Exception as exc:
            err = StorageError(f"pod peer host {owner} unavailable: {exc}")
            if guard is not None:
                guard.breaker.record_failure(err)
                return self._degraded_decide(
                    guard, counters, delta, load, kind
                )
            raise err from exc
        if isinstance(resp, dict) and resp.get(STALE_EPOCH):
            # rejected by a wrong-epoch owner (ISSUE 15): the lane
            # already ran the adoption hook; re-plan under the current
            # topology instead of failing the request
            return await self._stale_replan(
                namespace, ctx, delta, load, kind
            )
        if guard is not None:
            # A successful forward resets the consecutive-failure count
            # (the batchers do this per device batch on the admission
            # plane); without it, transient failures spread over hours
            # would accumulate to a trip.
            guard.breaker.record_success()
        if kind == "update_counters":
            return None
        return self._adopt(resp)

    # -- the limiter surface -------------------------------------------------

    def _psum_serves(self, namespace) -> bool:
        lane = self.psum_lane
        return lane is not None and str(namespace) in lane.namespaces

    async def check_rate_limited_and_update(
        self, namespace, ctx, delta: int, load_counters: bool = False
    ) -> CheckResult:
        if self._psum_serves(namespace):
            counters = _counters_that_apply(
                self._limiter.storage, Namespace.of(namespace), ctx
            )
            return self.psum_lane.check_and_update(
                counters, delta, load_counters
            )
        verdict, owner, counters = self._route(namespace, ctx)
        if verdict == LOCAL:
            return await self._local_check(
                namespace, ctx, delta, load_counters, counters
            )
        return await self._remote(
            owner, namespace, ctx, counters, delta, load_counters,
            "check_and_update",
        )

    async def is_rate_limited(self, namespace, ctx, delta: int) -> CheckResult:
        if self._psum_serves(namespace):
            counters = _counters_that_apply(
                self._limiter.storage, Namespace.of(namespace), ctx
            )
            return self.psum_lane.is_rate_limited(counters, delta)
        verdict, owner, counters = self._route(namespace, ctx)
        if verdict == LOCAL:
            return await self._local_is_limited(
                namespace, ctx, delta, counters
            )
        return await self._remote(
            owner, namespace, ctx, counters, delta, False,
            "is_rate_limited",
        )

    async def update_counters(self, namespace, ctx, delta: int) -> None:
        if self._psum_serves(namespace):
            counters = _counters_that_apply(
                self._limiter.storage, Namespace.of(namespace), ctx
            )
            self.psum_lane.update_counters(counters, delta)
            return
        verdict, owner, counters = self._route(namespace, ctx)
        if verdict == LOCAL:
            await self._local_update(namespace, ctx, delta, counters)
            return
        await self._remote(
            owner, namespace, ctx, counters, delta, False,
            "update_counters",
        )

    # -- telemetry -----------------------------------------------------------

    def resilience_stats(self) -> dict:
        degraded = journal = reconciles = replayed = open_count = 0
        reconcile_s = failover_s = 0.0
        for guard in self._guards.values():
            degraded += guard.degraded_decisions
            journal += guard.store.journal_size()
            reconciles += guard.reconciles
            replayed += guard.replayed_deltas
            reconcile_s += guard.reconcile_seconds
            failover_s += guard.breaker.open_seconds_total()
            if guard.breaker.state != BreakerState.CLOSED:
                open_count += 1
        return {
            "pod_failover_degraded_decisions": degraded,
            "pod_failover_journal_depth": journal,
            "pod_failover_breaker_open": open_count,
            "pod_failover_reconciles": reconciles,
            "pod_failover_replayed_deltas": replayed,
            "pod_failover_reconcile_seconds": round(reconcile_s, 6),
            "pod_failover_seconds": round(failover_s, 6),
        }

    def library_stats(self) -> dict:
        inner = getattr(self._limiter, "library_stats", None)
        stats = dict(inner()) if callable(inner) else {}
        stats.update(self.router.stats())
        stats.update(self.lane.stats())
        stats.update(self.resilience_stats())
        # pod observability plane (ISSUE 12): event counts (the
        # pod_events{kind} family feed), the last sequence number, and
        # the federated-signal gauges
        stats["pod_events"] = self.events.counts()
        stats["pod_event_seq"] = self.events.last_seq
        stats.update(self.aggregator.stats())
        if self.psum_lane is not None:
            stats.update(self.psum_lane.stats())
        if self.resize is not None:
            stats.update(self.resize.stats())
            stats["pod_resize_replans"] = self.stale_replans
        if self.standby is not None:
            stats.update(self.standby.stats())
        return stats

    def close_pod(self) -> None:
        if self.psum_lane is not None:
            self.psum_lane.close()
        self.lane.stop()


class _ForwardedLimit:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name


class _ForwardedCounter:
    """Header stand-in for a counter loaded on the owner host (exactly
    the fields CheckResult.response_header reads)."""

    __slots__ = (
        "max_value", "remaining", "expires_in", "window_seconds", "limit",
    )

    def __init__(self, max_value, remaining, expires_in, window, name):
        self.max_value = max_value
        self.remaining = remaining
        self.expires_in = expires_in
        self.window_seconds = window
        self.limit = _ForwardedLimit(name)


def psum_share_sender(lane: PeerLane, timeout: float = 2.0):
    """The publish half of parallel.PeerPsumTransport over this lane:
    a ``send(host, payload)`` callable for the transport's constructor.
    Runs on the psum pacer thread (a dedicated daemon) — admin_call's
    blocking control-plane RPC is fine there and never touches a
    serving loop."""

    def send(host: int, payload: bytes) -> None:
        lane.admin_call(host, {
            "kind": "psum_share",
            "from": lane.host_id,
            "payload": base64.b64encode(payload).decode(),
        }, timeout=timeout)

    return send
