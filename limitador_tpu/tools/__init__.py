"""Operational tools (migration, fleet helpers)."""
