"""One-shot migration of a reference (limitador) Redis keyspace into a
running limitador-tpu server.

THE REDIS INTEROP DECISION (VERDICT r3 #8 / r4 #8, in writing)
--------------------------------------------------------------
This framework deliberately does NOT speak RESP or emulate the
reference's Redis Lua scripts
(/root/reference/limitador/src/storage/redis/redis_async.rs:67-147,
scripts.rs:14-20). The shared-authority role Redis plays there is a
first-class native protocol here (`storage/authority.py`, msgpack over
gRPC, `--authority-listen`/`--authority-url`): re-implementing a Redis
client against a fake server would add a protocol surface nobody serves
in this stack while the semantics (atomic batched apply returning
authoritative values) already exist end-to-end. What a migrating fleet
actually needs is its LIVE COUNTERS moved over — this tool is that
path.

How it works: the reference stores one Redis string per counter — key =
``key_for_counter`` (version-prefixed postcard bytes, keys.rs:236-249),
value = the accumulated count, TTL = the window remainder. Our
`storage/keys.py` codec is byte-identical (proven in
tests/test_keys_postcard.py), so every key decodes against the same
limits YAML the fleet already ships, and the counts replay into a live
limitador-tpu server through POST /report (any storage, any topology,
no downtime).

Export on the Redis side. Counter keys are version-prefixed postcard
BYTES (arbitrary binary), so the export must never round-trip them
through shell variables — use a Redis client that hands back raw bytes
and base64-wrap before they touch the text dump (the reference fleet
already has redis-py wherever redis-cli lives)::

    python - <<'PY' > counters.dump
    import base64, redis
    r = redis.Redis()          # or redis.Redis.from_url("redis://...")
    for key in r.scan_iter(count=1000):
        value, pttl = r.get(key), r.pttl(key)
        if value is None or pttl is None or pttl <= 0:
            continue           # expired between SCAN and GET
        print(base64.b64encode(key).decode(), int(value), int(pttl))
    PY

Import here::

    python -m limitador_tpu.tools.redis_import \
        limits.yaml counters.dump --target http://127.0.0.1:8080

Semantics (documented contract):

* entries whose PTTL is <= 0 (expired / no TTL) or whose value field is
  ``nil``/missing (the key expired mid-export) are skipped and counted;
* keys that do not decode against the limits file are counted and
  reported, not fatal (the reference tolerates unknown keys the same
  way on scan);
* windows RESTART at import time with the full window length — the
  count carries over, the remaining-TTL does not. This errs strict
  (never over-admits during the cutover); exact-TTL carryover would
  need a storage-level backdoor that intentionally does not exist;
* ``/report`` is a delta-add, NOT idempotent — so on the first send
  failure the tool STOPS and writes every not-yet-sent entry
  (including the failed one) to ``<dump>.remaining`` in dump format;
  re-run on that file and nothing double-counts.
"""

from __future__ import annotations

import argparse
import base64
import binascii
import json
import re
import sys
import urllib.request
from typing import Dict, Iterable, List, Optional, Tuple

from ..core.counter import Counter
from ..server.limits_file import load_limits_file
from ..storage.keys import (
    LimitKeyIndex,
    key_for_counter,
    partial_counter_from_key,
)

__all__ = ["parse_dump", "decode_entries", "replay", "main"]


def parse_dump(
    lines: Iterable[str],
) -> Tuple[List[Tuple[bytes, int, int]], int]:
    """((key_bytes, value, pttl_ms) triples, nil_skipped) from export
    lines. Blank/comment lines are ignored; a line whose value field is
    ``nil`` or missing (key expired between SCAN and GET in a
    hand-rolled export) is SKIPPED and counted, not fatal; genuinely
    malformed lines raise with the line number."""
    out = []
    nil_skipped = 0
    for n, line in enumerate(lines, 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) == 3 and parts[1] == "nil":
            nil_skipped += 1
            continue
        if len(parts) != 3:
            # A 2-field line is ambiguous: "key pttl" (nil value from a
            # hand-rolled export) is indistinguishable from a TRUNCATED
            # "key value" whose counter would silently vanish — refuse
            # and make the operator look (only an explicit 'nil' value
            # field takes the skip path).
            raise ValueError(f"line {n}: expected 'key value pttl'")
        try:
            key = base64.b64decode(parts[0], validate=True)
        except (binascii.Error, ValueError) as exc:
            raise ValueError(f"line {n}: bad base64 key: {exc}") from None
        try:
            value, pttl = int(parts[1]), int(parts[2])
        except ValueError:
            raise ValueError(
                f"line {n}: value/pttl not integers"
            ) from None
        out.append((key, value, pttl))
    return out, nil_skipped


def decode_entries(
    entries: Iterable[Tuple[bytes, int, int]], limits
) -> Tuple[List[Tuple[Counter, int]], int, int]:
    """Decode dump triples against the configured limits. Returns
    (importable (counter, value) pairs, skipped_expired,
    skipped_unknown)."""
    index = LimitKeyIndex(limits)
    importable: List[Tuple[Counter, int]] = []
    expired = unknown = 0
    for key, value, pttl in entries:
        if pttl <= 0 or value <= 0:
            expired += 1
            continue
        counter = partial_counter_from_key(key, index)
        if counter is None:
            unknown += 1
            continue
        importable.append((counter, value))
    return importable, expired, unknown


# The HTTP API binds the request's values map as ``descriptors[0]``
# (server/http_api.py), so a counter keyed by the canonical
# ``descriptors[0].key`` variable forms replays as {key: value}. Other
# CEL shapes have no HTTP representation and are reported, not sent.
_DESC_VAR = re.compile(
    r"^descriptors\[0\]\.([A-Za-z_][\w]*)$"
    r"|^descriptors\[0\]\['([^']+)'\]$"
    r"|^descriptors\[0\]\[\"([^\"]+)\"\]$"
)


def values_for_replay(counter: Counter) -> Optional[Dict[str, str]]:
    """The /report ``values`` map reproducing this counter's variable
    bindings, or None when a variable expression has no HTTP form."""
    values: Dict[str, str] = {}
    for expr, value in counter.set_variables.items():
        m = _DESC_VAR.match(expr)
        if m is None:
            return None
        values[next(g for g in m.groups() if g is not None)] = value
    return values


def unreplayable_reason(
    counter: Counter, namespace_limits
) -> Tuple[Optional[str], int]:
    """Classify one (counter, limits) pair for replay through /report.

    The server re-selects limits by evaluating conditions against a
    context built ONLY from the counter's variable bindings — so a
    limit whose conditions reference descriptor fields absent from
    those bindings (e.g. ``descriptors[0].method == 'GET'`` on a
    counter keyed only by user) never matches during replay: its count
    would be silently dropped while OTHER limits in the namespace that
    happen to match the synthesized values got spuriously credited
    (ADVICE r5 medium finding). Simulate the server's selection here
    and refuse to send entries it would mis-credit.

    Returns ``(reason, extra_limits)``: reason is None (replayable),
    ``"shape"`` (a variable expression has no HTTP form) or
    ``"conditions"`` (the owning limit would not re-select, or would
    bind different variables); extra_limits counts OTHER limits the
    replayed report would also credit (a multi-credit warning, not a
    refusal — those limits would see this traffic in production too).
    """
    from ..core.cel import Context

    values = values_for_replay(counter)
    if values is None:
        return "shape", 0
    ctx = Context()
    ctx.list_binding("descriptors", [dict(values)])
    limit = counter.limit
    if not limit.applies(ctx):
        return "conditions", 0
    resolved = limit.resolve_variables(ctx)
    if resolved != dict(counter.set_variables):
        return "conditions", 0
    extra = 0
    for other in namespace_limits:
        if other == limit:
            continue
        if other.applies(ctx) and other.resolve_variables(ctx) is not None:
            extra += 1
    return None, extra


def dump_line(counter: Counter, value: int, pttl_ms: int = 1) -> str:
    """One dump-format line for (counter, value) — used to write the
    resumable remainder file."""
    return (
        base64.b64encode(key_for_counter(counter)).decode()
        + f" {int(value)} {int(pttl_ms)}"
    )


def replay(
    pairs: List[Tuple[Counter, int]],
    target: str,
    opener=None,
    limits=None,
    stats: Optional[Dict[str, int]] = None,
) -> Tuple[int, int, List[Tuple[Counter, int]], Optional[str]]:
    """POST each (counter, value) as a /report to the live server —
    counts land through the normal write path on any storage/topology.

    With ``limits`` (the fleet's configured limits), each entry is
    pre-flighted through :func:`unreplayable_reason`: entries whose
    owning limit would not be re-selected from the synthesized values
    (conditions over non-variable descriptor fields) are classified
    unreplayable — counted, warned about, NOT sent — instead of being
    silently dropped server-side while crediting the wrong limits.
    ``stats`` (optional dict) receives the breakdown: ``shape``,
    ``conditions``, ``multi_credit``.

    /report is a delta-add (NOT idempotent), so on the first send
    failure this STOPS and returns the unsent remainder instead of
    risking double-counts on a blind retry. Returns
    (sent, unreplayable, remaining_pairs, error)."""
    opener = opener or urllib.request.urlopen
    if stats is None:
        stats = {}
    stats.setdefault("shape", 0)
    stats.setdefault("conditions", 0)
    stats.setdefault("multi_credit", 0)
    by_ns: Dict[str, list] = {}
    for limit in limits or ():
        by_ns.setdefault(str(limit.namespace), []).append(limit)
    sent = unreplayable = 0
    for i, (counter, value) in enumerate(pairs):
        if limits is not None:
            reason, extra = unreplayable_reason(
                counter, by_ns.get(str(counter.namespace), ())
            )
            if reason is not None:
                unreplayable += 1
                stats[reason] += 1
                print(
                    f"unreplayable ({reason}): {counter.namespace} "
                    f"{dict(counter.set_variables)} +{value} — a /report "
                    "from these variable bindings would not re-select "
                    "this counter's limit",
                    file=sys.stderr,
                )
                continue
            if extra:
                stats["multi_credit"] += 1
                print(
                    f"warning: replaying {counter.namespace} "
                    f"{dict(counter.set_variables)} also credits "
                    f"{extra} other limit(s) in the namespace",
                    file=sys.stderr,
                )
        values = values_for_replay(counter)
        if values is None:
            unreplayable += 1
            stats["shape"] += 1
            continue
        body = json.dumps({
            "namespace": str(counter.namespace),
            "values": values,
            "delta": int(value),
        }).encode()
        req = urllib.request.Request(
            target.rstrip("/") + "/report",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with opener(req, timeout=30):
                sent += 1
        except Exception as exc:  # noqa: BLE001 — any transport failure
            return sent, unreplayable, list(pairs[i:]), repr(exc)
    return sent, unreplayable, [], None


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="limitador_tpu.tools.redis_import",
        description=(
            "Replay a reference-limitador Redis counter dump into a "
            "live limitador-tpu server (see module docstring for the "
            "redis-cli export script)."
        ),
    )
    parser.add_argument("limits_file", help="the fleet's limits YAML")
    parser.add_argument("dump", help="export file: base64key value pttl")
    parser.add_argument(
        "--target", default="http://127.0.0.1:8080",
        help="HTTP API base of the live server (default %(default)s)",
    )
    parser.add_argument(
        "--dry-run", action="store_true",
        help="decode and summarize, send nothing",
    )
    args = parser.parse_args(argv)

    limits = load_limits_file(args.limits_file)
    with open(args.dump) as f:
        entries, nil_skipped = parse_dump(f)
    pairs, expired, unknown = decode_entries(entries, limits)
    print(
        f"decoded {len(pairs)} live counters "
        f"({expired} expired skipped, {nil_skipped} nil-value skipped, "
        f"{unknown} unknown-key skipped)",
        file=sys.stderr,
    )
    if args.dry_run:
        for counter, value in pairs:
            print(f"{counter.namespace} {dict(counter.set_variables)} "
                  f"+{value}")
        return 0
    stats: Dict[str, int] = {}
    sent, unreplayable, remaining, error = replay(
        pairs, args.target, limits=limits, stats=stats
    )
    print(
        f"replayed {sent} counters into {args.target}"
        + (
            f" ({unreplayable} unreplayable NOT sent: "
            f"{stats.get('shape', 0)} with no HTTP variable form, "
            f"{stats.get('conditions', 0)} whose limit conditions "
            "reference descriptor fields absent from the counter's "
            "bindings; "
            f"{stats.get('multi_credit', 0)} sent with a multi-credit "
            "warning)"
            if unreplayable or stats.get("multi_credit")
            else ""
        ),
        file=sys.stderr,
    )
    if remaining:
        # /report deltas are not idempotent: save the unsent tail so the
        # operator re-runs on it without double-counting what landed.
        remainder_path = args.dump + ".remaining"
        with open(remainder_path, "w") as f:
            for counter, value in remaining:
                f.write(dump_line(counter, value) + "\n")
        print(
            f"send failed after {sent} counters ({error}); "
            f"{len(remaining)} unsent entries written to "
            f"{remainder_path} — fix the target and re-run on that file",
            file=sys.stderr,
        )
        return 1
    return 0 if not unreplayable else 2


if __name__ == "__main__":
    sys.exit(main())
