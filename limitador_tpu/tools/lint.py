"""Vendored AST linter — the fmt/clippy gate of this repo.

The reference enforces ``cargo fmt --check`` and ``clippy -D warnings``
in CI (/root/reference/.github/workflows/rust.yml). This image ships no
Python linter (no ruff/pyflakes/flake8, and installs are off), so — by
the same standard as the vendored HTTP/2, OTLP and reflection layers —
the gate is implemented from scratch on ``ast``:

* syntax errors (hard fail),
* unused imports (pyflakes F401 class: a name imported but never
  referenced in the module; ``__all__`` strings count as uses),
* redefined imports (same name imported twice in one scope),
* bare ``except:`` (clippy would call this a swallow-all),
* mutable default arguments (list/dict/set literals),
* comparisons to ``True``/``False``/``None`` with ``==``/``!=``,
* duplicate literal keys in dict displays,
* tabs in indentation and trailing whitespace,
* the metric-registry cross-check: every family a subsystem registers
  in a module-level ``METRIC_FAMILIES`` tuple (e.g.
  ``limitador_tpu/admission/__init__.py``) must be declared in
  ``observability/metrics.py``, and every declared ``admission_*``
  family must appear in the admission registry — a typo'd or orphaned
  family fails the gate instead of silently never rendering,
* the native-phase cross-check: every entry of the telemetry plane's
  ``PHASES`` tuple (observability/native_plane.py) must have a matching
  ``native_phase_<entry>`` histogram family declared in metrics.py and
  registered in the plane's ``METRIC_FAMILIES``,
* the buffer-donation check: ``jax.jit`` call sites in the kernel
  modules (DONATION_CHECKED_MODULES) whose wrapped function carries the
  counter table (a ``state`` or ``values``/``expiry`` parameter) must
  pass ``donate_argnums`` — a missing donation silently turns every
  table-mutating launch into a full-table copy (8 bytes/slot/batch of
  HBM traffic). Read-only kernels are allowlisted in DONATION_EXEMPT.

``# noqa`` anywhere on the offending line suppresses that finding.
Run: ``python -m limitador_tpu.tools.lint [paths...]`` (defaults to the
repo's lintable set); exit 1 on any finding — ``make check`` and
``tests/test_lint.py`` both ride this.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Tuple

__all__ = [
    "lint_file", "lint_paths", "lint_metric_registry", "lint_donation",
    "lint_ctypes_signatures", "lint_native_phases",
    "lint_debug_sections", "main",
]

DEFAULT_TARGETS = ("limitador_tpu", "tests", "bench.py",
                   "__graft_entry__.py")

#: metric prefixes whose declarations must be covered by a subsystem
#: METRIC_FAMILIES registry (prefix -> registry module, repo-relative)
REGISTRY_OWNED_PREFIXES = {
    "admission_": "limitador_tpu/admission/__init__.py",
    "plan_cache_": "limitador_tpu/tpu/plan_cache.py",
    "sharded_": "limitador_tpu/tpu/sharded.py",
    "dispatch_chunk_": "limitador_tpu/tpu/batcher.py",
    "native_lane_": "limitador_tpu/tpu/native_pipeline.py",
    "lease_": "limitador_tpu/lease/__init__.py",
    "native_phase_": "limitador_tpu/observability/native_plane.py",
    "slo_": "limitador_tpu/observability/native_plane.py",
    "tenant_": "limitador_tpu/observability/usage.py",
    "signal_": "limitador_tpu/observability/signals.py",
}

#: the native telemetry plane's phase registry: every entry of this
#: module-level PHASES tuple must have a ``native_phase_<entry>``
#: histogram family declared in metrics.py AND registered in the same
#: module's METRIC_FAMILIES — a phase added to the C enum without its
#: Prometheus family would silently drop that phase's drain.
NATIVE_PLANE_MODULE = "limitador_tpu/observability/native_plane.py"

#: the HTTP API module whose /debug/stats sections must be registered
#: in its DEBUG_STATS_SECTIONS tuple (lint_debug_sections — the
#: lint_native_phases pattern generalized to the debug surface)
HTTP_API_MODULE = "limitador_tpu/server/http_api.py"

#: native sources whose extern "C" exports must carry matching ctypes
#: declarations in the binding modules (symbol prefix filters the
#: internal helpers out)
CTYPES_SOURCES = ("native/hostpath.cc", "native/h2ingress.cc")
CTYPES_BINDINGS = (
    "limitador_tpu/native/__init__.py",
    "limitador_tpu/native/ingress.py",
)
CTYPES_SYMBOL_PREFIXES = ("hp_", "h2i_")

#: modules whose jax.jit sites must donate table-carrying buffers
DONATION_CHECKED_MODULES = (
    "limitador_tpu/ops/kernel.py",
    "limitador_tpu/parallel/mesh.py",
    "limitador_tpu/tpu/replicated.py",
)

#: table parameter names that mark a kernel as table-carrying ("hits"
#: is the per-slot traffic accumulator column — same in-place contract)
DONATION_PARAMS = frozenset({"state", "values", "expiry", "hits"})

#: read-only kernels: they take the table but never produce a new one,
#: so there is nothing to update in place
DONATION_EXEMPT = frozenset({"read_slots"})


def declared_metric_families(metrics_path: Path):
    """Family names declared in observability/metrics.py: the first
    string-literal argument of every Counter/Gauge/Histogram call."""
    tree = ast.parse(metrics_path.read_text(), filename=str(metrics_path))
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        fname = (
            fn.id if isinstance(fn, ast.Name)
            else fn.attr if isinstance(fn, ast.Attribute) else None
        )
        if fname in ("Counter", "Gauge", "Histogram") and node.args:
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                names.add(first.value)
    return names


def registered_metric_families(package_root: Path):
    """(path, lineno, name) for every entry of a module-level
    ``METRIC_FAMILIES`` tuple/list under the package."""
    out = []
    for path in sorted(package_root.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError:
            continue  # reported by lint_file
        for node in tree.body:
            if not (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "METRIC_FAMILIES"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                continue
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    out.append((path, elt.lineno, elt.value))
    return out


def lint_metric_registry(repo_root: Path) -> List[str]:
    """Cross-check subsystem METRIC_FAMILIES registries against the
    PrometheusMetrics declarations (both directions for the prefixes in
    REGISTRY_OWNED_PREFIXES)."""
    metrics_path = repo_root / "limitador_tpu" / "observability" / "metrics.py"
    package_root = repo_root / "limitador_tpu"
    if not metrics_path.exists():
        return []
    declared = declared_metric_families(metrics_path)
    registered = registered_metric_families(package_root)
    findings = []
    for path, lineno, name in registered:
        if name not in declared:
            findings.append(
                f"{path}:{lineno}: metric family '{name}' is registered "
                "but not declared in observability/metrics.py"
            )
    registered_names = {name for _p, _l, name in registered}
    for prefix, registry in sorted(REGISTRY_OWNED_PREFIXES.items()):
        for name in sorted(declared):
            if name.startswith(prefix) and name not in registered_names:
                findings.append(
                    f"{metrics_path}:0: metric family '{name}' is "
                    f"declared but missing from {registry}'s "
                    "METRIC_FAMILIES registry"
                )
    return findings


def _module_string_tuple(path: Path, name: str) -> List[str]:
    """Entries of a module-level ``NAME = ("a", "b", ...)`` tuple/list
    assignment (string constants only)."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return []
    out: List[str] = []
    for node in tree.body:
        if not (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets
            )
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            continue
        for elt in node.value.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
    return out


def lint_native_phases(repo_root: Path) -> List[str]:
    """Cross-check the native telemetry plane's PHASES tuple: every
    phase needs a ``native_phase_<phase>`` histogram family declared in
    observability/metrics.py and registered in native_plane's
    METRIC_FAMILIES — otherwise that phase's drain silently never
    renders."""
    plane_path = repo_root / NATIVE_PLANE_MODULE
    metrics_path = (
        repo_root / "limitador_tpu" / "observability" / "metrics.py"
    )
    if not plane_path.exists() or not metrics_path.exists():
        return []
    phases = _module_string_tuple(plane_path, "PHASES")
    registered = set(_module_string_tuple(plane_path, "METRIC_FAMILIES"))
    declared = declared_metric_families(metrics_path)
    findings = []
    for phase in phases:
        family = f"native_phase_{phase}"
        if family not in declared:
            findings.append(
                f"{plane_path}:0: PHASES entry '{phase}' has no "
                f"'{family}' histogram family declared in "
                "observability/metrics.py"
            )
        if family not in registered:
            findings.append(
                f"{plane_path}:0: PHASES entry '{phase}' has no "
                f"'{family}' entry in METRIC_FAMILIES"
            )
    return findings


def _debug_section_tuples(path: Path, name: str) -> List[str]:
    """First elements of a module-level ``NAME = (("k", "attr"), ...)``
    tuple-of-pairs assignment."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return []
    out: List[str] = []
    for node in tree.body:
        if not (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets
            )
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            continue
        for elt in node.value.elts:
            if (
                isinstance(elt, (ast.Tuple, ast.List)) and elt.elts
                and isinstance(elt.elts[0], ast.Constant)
                and isinstance(elt.elts[0].value, str)
            ):
                out.append(elt.elts[0].value)
    return out


def lint_debug_sections(repo_root: Path) -> List[str]:
    """Cross-check the /debug/stats section registry (the
    lint_native_phases pattern generalized to the debug surface): every
    section http_api.py serves — a ``stats["..."] = ...`` literal store
    or a DEBUG_SOURCE_SECTIONS entry — must appear in its
    DEBUG_STATS_SECTIONS tuple, and every registered name must actually
    be served. A renamed or orphaned section fails the gate instead of
    silently vanishing from the endpoint dashboards and benches
    scrape."""
    api_path = repo_root / HTTP_API_MODULE
    if not api_path.exists():
        return []
    registered = set(_module_string_tuple(api_path, "DEBUG_STATS_SECTIONS"))
    served: dict = {}  # name -> lineno
    for name in _debug_section_tuples(api_path, "DEBUG_SOURCE_SECTIONS"):
        served.setdefault(name, 0)
    try:
        tree = ast.parse(api_path.read_text(), filename=str(api_path))
    except SyntaxError:
        return []  # reported by lint_file
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Subscript)
        ):
            continue
        target = node.targets[0]
        if not (
            isinstance(target.value, ast.Name)
            and target.value.id == "stats"
        ):
            continue
        sl = target.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            served.setdefault(sl.value, node.lineno)
    findings = []
    for name, lineno in sorted(served.items()):
        if name not in registered:
            findings.append(
                f"{api_path}:{lineno}: /debug/stats section '{name}' is "
                "served but missing from DEBUG_STATS_SECTIONS"
            )
    for name in sorted(registered - set(served)):
        findings.append(
            f"{api_path}:0: DEBUG_STATS_SECTIONS entry '{name}' is "
            "registered but never served by get_debug_stats"
        )
    return findings


def exported_c_symbols(source: str):
    """(name, return_type, has_params) for every exported C function in
    a translation unit (prefix-filtered; extern "C" definitions in this
    repo all sit at column 0 with the return type on the same line)."""
    import re

    out = []
    pattern = re.compile(
        r"^([A-Za-z_][A-Za-z0-9_]*\s*\**)\s+("
        + "|".join(p + r"[a-z0-9_]+" for p in CTYPES_SYMBOL_PREFIXES)
        + r")\s*\(([^)]*)",
        re.MULTILINE,
    )
    for match in pattern.finditer(source):
        ret = match.group(1).replace(" ", "")
        name = match.group(2)
        params = match.group(3).strip()
        # multi-line parameter lists never close on the match line; an
        # empty first-line capture with more lines following still means
        # "has params" only when the very next char isn't ')'
        has_params = params not in ("", "void")
        out.append((name, ret, has_params))
    return out


def declared_ctypes_signatures(source: str):
    """{symbol: {"restype", "argtypes"}} assignments in a binding
    module (``lib.<symbol>.restype = ...`` / ``.argtypes = ...``)."""
    import re

    out: dict = {}
    for match in re.finditer(
        r"lib\.([A-Za-z_][A-Za-z0-9_]*)\.(restype|argtypes)\s*=", source
    ):
        out.setdefault(match.group(1), set()).add(match.group(2))
    return out


def lint_ctypes_signatures(repo_root: Path) -> List[str]:
    """Signature-drift gate for the native ABI: every symbol exported
    from the C sources must have a ctypes ``argtypes`` declaration on
    the Python side (non-void returns also need ``restype``), and every
    Python-side declaration must name a symbol that still exists — a
    renamed/removed export fails the gate instead of segfaulting at
    call time."""
    findings: List[str] = []
    exported: dict = {}
    for rel in CTYPES_SOURCES:
        path = repo_root / rel
        if not path.exists():
            continue
        for name, ret, has_params in exported_c_symbols(path.read_text()):
            exported[name] = (rel, ret, has_params)
    declared: dict = {}
    for rel in CTYPES_BINDINGS:
        path = repo_root / rel
        if not path.exists():
            continue
        for name, kinds in declared_ctypes_signatures(
            path.read_text()
        ).items():
            declared.setdefault(name, set()).update(kinds)
    if not exported or not declared:
        return findings
    for name, (rel, ret, has_params) in sorted(exported.items()):
        kinds = declared.get(name)
        if kinds is None:
            findings.append(
                f"{rel}: exported symbol '{name}' has no ctypes "
                "declaration in the binding modules (drift: a call "
                "through the default int-sized signature corrupts "
                "arguments silently)"
            )
            continue
        if has_params and "argtypes" not in kinds:
            findings.append(
                f"{rel}: exported symbol '{name}' takes parameters but "
                "the binding declares no argtypes"
            )
        if ret != "void" and "restype" not in kinds:
            findings.append(
                f"{rel}: exported symbol '{name}' returns {ret} but the "
                "binding declares no restype (ctypes truncates to int)"
            )
    for name in sorted(declared):
        if not name.startswith(CTYPES_SYMBOL_PREFIXES):
            continue
        if name not in exported:
            findings.append(
                f"limitador_tpu/native: binding declares '{name}' but no "
                "native source exports it (renamed or removed symbol)"
            )
    return findings


def _is_jax_jit(node) -> bool:
    return (
        isinstance(node, ast.Attribute) and node.attr == "jit"
        and isinstance(node.value, ast.Name) and node.value.id == "jax"
    )


def lint_donation(repo_root: Path) -> List[str]:
    """Flag ``jax.jit`` call sites in the kernel modules whose wrapped
    function carries the counter table (DONATION_PARAMS) but passes no
    ``donate_argnums``: without donation XLA copies the whole table on
    every launch instead of updating it in place. Covers the three site
    shapes the kernels use — ``@jax.jit``, ``@functools.partial(jax.jit,
    ...)`` and ``functools.partial(jax.jit, ...)(fn)`` — and allowlists
    the read-only kernels (DONATION_EXEMPT)."""
    findings: List[str] = []
    for rel in DONATION_CHECKED_MODULES:
        path = repo_root / rel
        if not path.exists():
            continue
        src = path.read_text()
        try:
            tree = ast.parse(src, filename=str(path))
        except SyntaxError:
            continue  # reported by lint_file
        lines = src.splitlines()
        funcs = {
            node.name: node
            for node in ast.walk(tree)
            if isinstance(node, ast.FunctionDef)
        }

        def check(lineno: int, kwargs, fn_name: str) -> None:
            fn_node = funcs.get(fn_name)
            if fn_node is None or fn_name in DONATION_EXEMPT:
                return
            params = sorted(
                {a.arg for a in fn_node.args.args} & DONATION_PARAMS
            )
            if not params or "donate_argnums" in kwargs:
                return
            if 0 < lineno <= len(lines) and "# noqa" in lines[lineno - 1]:
                return
            findings.append(
                f"{path}:{lineno}: jax.jit site for table-carrying "
                f"kernel '{fn_name}' (params {params}) passes no "
                "donate_argnums — every launch would copy the counter "
                "table instead of updating it in place"
            )

        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    if _is_jax_jit(dec):
                        check(dec.lineno, set(), node.name)
                    elif isinstance(dec, ast.Call):
                        kwargs = {k.arg for k in dec.keywords}
                        if _is_jax_jit(dec.func):
                            check(dec.lineno, kwargs, node.name)
                        elif (
                            isinstance(dec.func, ast.Attribute)
                            and dec.func.attr == "partial"
                            and dec.args and _is_jax_jit(dec.args[0])
                        ):
                            check(dec.lineno, kwargs, node.name)
            elif isinstance(node, ast.Call):
                func = node.func
                wrapped = (
                    node.args[0].id
                    if node.args and isinstance(node.args[0], ast.Name)
                    else None
                )
                if wrapped is None:
                    continue
                if (
                    isinstance(func, ast.Call)
                    and isinstance(func.func, ast.Attribute)
                    and func.func.attr == "partial"
                    and func.args and _is_jax_jit(func.args[0])
                ):
                    # functools.partial(jax.jit, ...)(fn)
                    check(
                        node.lineno, {k.arg for k in func.keywords}, wrapped
                    )
                elif _is_jax_jit(func):
                    # jax.jit(fn, ...)
                    check(
                        node.lineno, {k.arg for k in node.keywords}, wrapped
                    )
    return findings


def _imported_bindings(tree: ast.AST):
    """(lineno, bound_name, scope_id) for every import; scope_id keys
    the nearest enclosing function/class/module, so a deliberate lazy
    re-import inside a function never collides with the module scope
    (pyflakes F811 is same-scope only too)."""
    out = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.scope = [id(tree)]

        def visit_Import(self, node):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                # redef key keeps the dotted path: `import urllib.request`
                # and `import urllib.error` both bind 'urllib' on purpose
                out.append(
                    (node.lineno, bound, alias.name, self.scope[-1])
                )

        def visit_ImportFrom(self, node):
            if node.module == "__future__":
                return  # compiler directive, not a binding
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name
                out.append(
                    (node.lineno, bound, bound, self.scope[-1])
                )

        def _scoped(self, node):
            self.scope.append(id(node))
            self.generic_visit(node)
            self.scope.pop()

        visit_FunctionDef = _scoped
        visit_AsyncFunctionDef = _scoped
        visit_ClassDef = _scoped
        visit_Lambda = _scoped

    V().visit(tree)
    return out


def _used_names(tree: ast.AST):
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # "a.b.c" usage roots at the Name, already collected
            pass
        elif isinstance(node, ast.Assign):
            for target in node.targets:
                if (
                    isinstance(target, ast.Name)
                    and target.id == "__all__"
                    and isinstance(node.value, (ast.List, ast.Tuple))
                ):
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            used.add(elt.value)
    return used


def lint_file(path: Path) -> List[Tuple[int, str]]:
    src = path.read_text()
    lines = src.splitlines()

    def suppressed(lineno: int) -> bool:
        return (
            0 < lineno <= len(lines) and "# noqa" in lines[lineno - 1]
        )

    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as exc:
        return [(exc.lineno or 0, f"syntax error: {exc.msg}")]

    findings: List[Tuple[int, str]] = []

    # unused + same-scope-redefined imports
    bindings = _imported_bindings(tree)
    used = _used_names(tree)
    seen: dict = {}
    for lineno, name, full, scope in bindings:
        key = (full, scope)
        if key in seen and not suppressed(lineno):
            findings.append(
                (lineno, f"import '{name}' redefines line {seen[key]}")
            )
        seen.setdefault(key, lineno)
    for lineno, name, _full, _scope in bindings:
        if name not in used and not suppressed(lineno):
            findings.append((lineno, f"unused import '{name}'"))

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            if not suppressed(node.lineno):
                findings.append(
                    (node.lineno, "bare 'except:' swallows everything")
                )
        elif isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            for default in (
                list(node.args.defaults) + list(node.args.kw_defaults)
            ):
                if isinstance(
                    default, (ast.List, ast.Dict, ast.Set)
                ) and not suppressed(default.lineno):
                    findings.append((
                        default.lineno,
                        f"mutable default argument in '{node.name}'",
                    ))
        elif isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if (
                    isinstance(op, (ast.Eq, ast.NotEq))
                    and isinstance(comp, ast.Constant)
                    and (comp.value is None or comp.value is True
                         or comp.value is False)
                    and not suppressed(node.lineno)
                ):
                    findings.append((
                        node.lineno,
                        f"comparison to {comp.value!r} with ==/!= "
                        "(use is/is not or truthiness)",
                    ))
        elif isinstance(node, ast.Dict):
            keys = [
                k.value
                for k in node.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, (str, int))
            ]
            dupes = {k for k in keys if keys.count(k) > 1}
            if dupes and not suppressed(node.lineno):
                findings.append((
                    node.lineno,
                    f"duplicate dict keys: {sorted(map(repr, dupes))}",
                ))

    for i, line in enumerate(lines, 1):
        if "# noqa" in line:
            continue
        stripped = line.rstrip("\n")
        if stripped != stripped.rstrip():
            findings.append((i, "trailing whitespace"))
        indent = stripped[: len(stripped) - len(stripped.lstrip())]
        if "\t" in indent:
            findings.append((i, "tab in indentation"))

    return sorted(findings)


def _iter_files(targets) -> List[Path]:
    files = []
    for target in targets:
        p = Path(target)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    # generated protobuf output is protoc's style, not ours
    return [f for f in files if not f.name.endswith("_pb2.py")
            and not f.name.endswith("_pb2_grpc.py")]


def lint_paths(targets) -> List[str]:
    out = []
    for f in _iter_files(targets):
        for lineno, msg in lint_file(f):
            out.append(f"{f}:{lineno}: {msg}")
    return out


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    targets = argv or list(DEFAULT_TARGETS)
    findings = lint_paths(targets)
    repo_root = Path(__file__).resolve().parent.parent.parent
    findings.extend(lint_metric_registry(repo_root))
    findings.extend(lint_donation(repo_root))
    findings.extend(lint_ctypes_signatures(repo_root))
    findings.extend(lint_native_phases(repo_root))
    findings.extend(lint_debug_sections(repo_root))
    for finding in findings:
        print(finding)
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
