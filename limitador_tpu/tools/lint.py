"""Compatibility shim over ``limitador_tpu.tools.analysis`` (ISSUE 9).

The five ad-hoc passes that lived here (style, metric-registry,
donation, ctypes-ABI drift, native-phase/debug-section cross-checks)
now ride the pass-registry framework in ``tools/analysis/`` alongside
the lock-order, buffer-safety and tracing-safety analyzers. This module
keeps the historical entry points — ``python -m
limitador_tpu.tools.lint``, ``make lint``, and the function API
``tests/`` import — delegating to the registry, with byte-compatible
legacy string rendering ("path:lineno: message").

New passes register in ``tools/analysis/``; see ``docs/analysis.md``.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import List

from .analysis import RepoContext
from .analysis.donation import (          # noqa: re-exported legacy API
    DONATION_CHECKED_MODULES, DONATION_EXEMPT, DONATION_PARAMS,
    donation_findings,
)
from .analysis.native_abi import (        # noqa: re-exported legacy API
    CTYPES_BINDINGS, CTYPES_SOURCES, CTYPES_SYMBOL_PREFIXES,
    abi_findings, declared_ctypes_signatures, exported_c_symbols,
)
from .analysis.registries import (        # noqa: re-exported legacy API
    HTTP_API_MODULE, NATIVE_PLANE_MODULE, OBSERVABILITY_DOC,
    REGISTRY_OWNED_PREFIXES, debug_section_findings, docs_sync_findings,
    metric_registry_findings, native_phase_findings,
)
from .analysis.style import lint_file, lint_paths  # noqa: re-exported

__all__ = [
    "lint_file", "lint_paths", "lint_metric_registry", "lint_donation",
    "lint_ctypes_signatures", "lint_native_phases",
    "lint_debug_sections", "lint_docs_sync", "main", "DEFAULT_TARGETS",
]

DEFAULT_TARGETS = ("limitador_tpu", "tests", "bench.py",
                   "__graft_entry__.py")


def _legacy(ctx: RepoContext, findings) -> List[str]:
    """Render registry findings in the historical string format."""
    out = []
    for f in findings:
        path = f.path
        if not Path(path).is_absolute():
            path = str(ctx.root / path)
        out.append(f"{path}:{f.line}: {f.message}")
    return out


def lint_metric_registry(repo_root) -> List[str]:
    ctx = RepoContext(repo_root)
    return _legacy(ctx, metric_registry_findings(ctx))


def lint_native_phases(repo_root) -> List[str]:
    ctx = RepoContext(repo_root)
    return _legacy(ctx, native_phase_findings(ctx))


def lint_debug_sections(repo_root) -> List[str]:
    ctx = RepoContext(repo_root)
    return _legacy(ctx, debug_section_findings(ctx))


def lint_docs_sync(repo_root) -> List[str]:
    ctx = RepoContext(repo_root)
    return _legacy(ctx, docs_sync_findings(ctx))


def lint_ctypes_signatures(repo_root) -> List[str]:
    # legacy format for this pass: repo-relative path, NO line prefix
    # ("native/hostpath.cc: exported symbol ...")
    ctx = RepoContext(repo_root)
    return [f"{f.path}: {f.message}" for f in abi_findings(ctx)]


def lint_donation(repo_root) -> List[str]:
    ctx = RepoContext(repo_root)
    return _legacy(ctx, donation_findings(ctx))


def main(argv=None) -> int:
    """Historical CLI: now the full analysis gate (every registered
    pass, baseline applied). ``python -m limitador_tpu.tools.analysis``
    is the first-class interface with --list/--only/--json."""
    from .analysis.__main__ import main as analysis_main

    argv = list(sys.argv[1:] if argv is None else argv)
    return analysis_main(argv)


if __name__ == "__main__":
    sys.exit(main())
