"""Registry cross-check passes: metric families, native phases, debug
sections.

Three instances of the same shape — a subsystem declares a module-level
registry tuple, another module consumes it, and drift in either
direction (a typo'd family that never renders, an orphaned registration
nothing serves) must fail the gate instead of silently vanishing from
dashboards. Ported from ``tools/lint.py`` (PR 2 / PR 7 / PR 8).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List

from . import Finding, RepoContext, register_pass

__all__ = [
    "REGISTRY_OWNED_PREFIXES", "NATIVE_PLANE_MODULE", "HTTP_API_MODULE",
    "OBSERVABILITY_DOC", "EVENTS_MODULE",
    "declared_metric_families", "registered_metric_families",
    "metric_registry_findings", "native_phase_findings",
    "debug_section_findings", "docs_sync_findings",
]

#: metric prefixes whose declarations must be covered by a subsystem
#: METRIC_FAMILIES registry (prefix -> registry module, repo-relative)
REGISTRY_OWNED_PREFIXES = {
    "admission_": "limitador_tpu/admission/__init__.py",
    "plan_cache_": "limitador_tpu/tpu/plan_cache.py",
    "peer_health_": "limitador_tpu/server/peering.py",
    "pod_": "limitador_tpu/routing.py",
    # pod observability plane (ISSUE 12): hop breakdown + federated
    # signals own pod_hop_/pod_signal_; the event timeline owns
    # pod_event (covers pod_events + pod_event_seq)
    "pod_hop_": "limitador_tpu/observability/pod_plane.py",
    "pod_signal_": "limitador_tpu/observability/pod_plane.py",
    "pod_event": "limitador_tpu/observability/events.py",
    # elastic pod (ISSUE 15): the live membership-transition plane
    "pod_resize_": "limitador_tpu/server/resize.py",
    "sharded_": "limitador_tpu/tpu/sharded.py",
    "dispatch_chunk_": "limitador_tpu/tpu/batcher.py",
    "native_lane_": "limitador_tpu/tpu/native_pipeline.py",
    "lease_": "limitador_tpu/lease/__init__.py",
    "native_phase_": "limitador_tpu/observability/native_plane.py",
    "slo_": "limitador_tpu/observability/native_plane.py",
    "tenant_": "limitador_tpu/observability/usage.py",
    "signal_": "limitador_tpu/observability/signals.py",
    # serving-model observatory (ISSUE 14): the online coefficient
    # fit's model_* gauges and the capacity_* headroom forecast
    "model_": "limitador_tpu/observability/model.py",
    "capacity_": "limitador_tpu/observability/model.py",
    # flight recorder (ISSUE 16): exemplar rings, trigger tallies and
    # the incident-bundle spool
    "flight_": "limitador_tpu/observability/flight.py",
    # tiered storage (ISSUE 17): per-tier residency, migration rates
    # and the cold-tier decide latency
    "tier_": "limitador_tpu/tier/__init__.py",
    # fast join (ISSUE 18): the join counters live on the resize
    # coordinator (one membership plane, one owner); the warm-up
    # plane owns standby_*
    "join_": "limitador_tpu/server/resize.py",
    "standby_": "limitador_tpu/server/standby.py",
    # capacity controller (ISSUE 20): knob gauges, actuation tallies
    # and the interlock/objective/pressure surfaces
    "ctl_": "limitador_tpu/control/__init__.py",
}

#: the native telemetry plane's phase registry module
NATIVE_PLANE_MODULE = "limitador_tpu/observability/native_plane.py"

#: the HTTP API module whose /debug/stats sections must be registered
#: in its DEBUG_STATS_SECTIONS tuple
HTTP_API_MODULE = "limitador_tpu/server/http_api.py"

METRICS_MODULE = "limitador_tpu/observability/metrics.py"

#: the human-facing observability reference every telemetry surface
#: must appear in (docs-sync pass, ISSUE 16)
OBSERVABILITY_DOC = "docs/observability.md"

#: the typed pod event registry whose kinds the doc must enumerate
EVENTS_MODULE = "limitador_tpu/observability/events.py"


def declared_metric_families(ctx: RepoContext):
    """Family names declared in observability/metrics.py: the first
    string-literal argument of every Counter/Gauge/Histogram call."""
    path = ctx.path(METRICS_MODULE)
    names = set()
    if ctx.tree(path) is None:
        return names
    for node in ctx.nodes(path):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        fname = (
            fn.id if isinstance(fn, ast.Name)
            else fn.attr if isinstance(fn, ast.Attribute) else None
        )
        if fname in ("Counter", "Gauge", "Histogram") and node.args:
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                names.add(first.value)
    return names


def registered_metric_families(ctx: RepoContext):
    """(path, lineno, name) for every entry of a module-level
    ``METRIC_FAMILIES`` tuple/list under the package."""
    out = []
    for path in ctx.package_files():
        tree = ctx.tree(path)
        if tree is None:
            continue  # reported by the style pass
        for node in tree.body:
            if not (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "METRIC_FAMILIES"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                continue
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    out.append((path, elt.lineno, elt.value))
    return out


def metric_registry_findings(ctx: RepoContext) -> List[Finding]:
    if not ctx.path(METRICS_MODULE).exists():
        return []
    declared = declared_metric_families(ctx)
    registered = registered_metric_families(ctx)
    findings = []
    for path, lineno, name in registered:
        if name not in declared:
            findings.append(Finding(
                "metric-registry", ctx.rel(path), lineno,
                f"metric family '{name}' is registered but not declared "
                "in observability/metrics.py",
                hint="declare the Counter/Gauge/Histogram in "
                     "PrometheusMetrics, or drop the registry entry",
            ))
    registered_names = {name for _p, _l, name in registered}
    for prefix, registry in sorted(REGISTRY_OWNED_PREFIXES.items()):
        for name in sorted(declared):
            if name.startswith(prefix) and name not in registered_names:
                findings.append(Finding(
                    "metric-registry", METRICS_MODULE, 0,
                    f"metric family '{name}' is declared but missing "
                    f"from {registry}'s METRIC_FAMILIES registry",
                    hint=f"add '{name}' to METRIC_FAMILIES in {registry}",
                ))
    return findings


def native_phase_findings(ctx: RepoContext) -> List[Finding]:
    plane = ctx.path(NATIVE_PLANE_MODULE)
    if not plane.exists() or not ctx.path(METRICS_MODULE).exists():
        return []
    phases = ctx.module_string_tuple(plane, "PHASES")
    registered = set(ctx.module_string_tuple(plane, "METRIC_FAMILIES"))
    declared = declared_metric_families(ctx)
    findings = []
    for phase in phases:
        family = f"native_phase_{phase}"
        if family not in declared:
            findings.append(Finding(
                "native-phases", NATIVE_PLANE_MODULE, 0,
                f"PHASES entry '{phase}' has no '{family}' histogram "
                "family declared in observability/metrics.py",
                hint="a phase without its family silently drops that "
                     "phase's drain — declare the histogram",
            ))
        if family not in registered:
            findings.append(Finding(
                "native-phases", NATIVE_PLANE_MODULE, 0,
                f"PHASES entry '{phase}' has no '{family}' entry in "
                "METRIC_FAMILIES",
                hint=f"register '{family}' in native_plane's "
                     "METRIC_FAMILIES",
            ))
    return findings


def _debug_section_pairs(ctx: RepoContext, path: Path, name: str):
    """First elements of a module-level ``NAME = (("k", "attr"), ...)``
    tuple-of-pairs assignment."""
    tree = ctx.tree(path)
    if tree is None:
        return []
    out: List[str] = []
    for node in tree.body:
        if not (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets
            )
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            continue
        for elt in node.value.elts:
            if (
                isinstance(elt, (ast.Tuple, ast.List)) and elt.elts
                and isinstance(elt.elts[0], ast.Constant)
                and isinstance(elt.elts[0].value, str)
            ):
                out.append(elt.elts[0].value)
    return out


def debug_section_findings(ctx: RepoContext) -> List[Finding]:
    api_path = ctx.path(HTTP_API_MODULE)
    if not api_path.exists():
        return []
    registered = set(
        ctx.module_string_tuple(api_path, "DEBUG_STATS_SECTIONS")
    )
    served: dict = {}  # name -> lineno
    for name in _debug_section_pairs(ctx, api_path, "DEBUG_SOURCE_SECTIONS"):
        served.setdefault(name, 0)
    tree = ctx.tree(api_path)
    if tree is None:
        return []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Subscript)
        ):
            continue
        target = node.targets[0]
        if not (
            isinstance(target.value, ast.Name)
            and target.value.id == "stats"
        ):
            continue
        sl = target.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            served.setdefault(sl.value, node.lineno)
    findings = []
    for name, lineno in sorted(served.items()):
        if name not in registered:
            findings.append(Finding(
                "debug-sections", HTTP_API_MODULE, lineno,
                f"/debug/stats section '{name}' is served but missing "
                "from DEBUG_STATS_SECTIONS",
                hint="register it so dashboards and benches can rely "
                     "on the section set",
            ))
    for name in sorted(registered - set(served)):
        findings.append(Finding(
            "debug-sections", HTTP_API_MODULE, 0,
            f"DEBUG_STATS_SECTIONS entry '{name}' is registered but "
            "never served by get_debug_stats",
            hint="serve the section or drop the registration",
        ))
    return findings


def _debug_routes(ctx: RepoContext, path: Path):
    """(route, lineno) for every ``/debug/*`` string literal passed to
    ``router.add_get``/``add_post`` in the HTTP API module."""
    tree = ctx.tree(path)
    out = []
    if tree is None:
        return out
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("add_get", "add_post")
            and node.args
        ):
            continue
        first = node.args[0]
        if (
            isinstance(first, ast.Constant)
            and isinstance(first.value, str)
            and first.value.startswith("/debug")
        ):
            out.append((first.value, node.lineno))
    return out


def docs_sync_findings(ctx: RepoContext) -> List[Finding]:
    """Every telemetry surface must appear in docs/observability.md:
    each EVENT_KINDS entry, each registered METRIC_FAMILIES family, and
    each /debug route the HTTP API serves. A surface shipped without its
    doc line is invisible to the operator who needs it during an
    incident — exactly when nobody reads source. Trees without the doc
    (synthetic lint fixtures) are exempt."""
    doc_path = ctx.path(OBSERVABILITY_DOC)
    if not doc_path.exists():
        return []
    doc = ctx.source(doc_path)
    findings = []
    events_path = ctx.path(EVENTS_MODULE)
    if events_path.exists():
        for kind in ctx.module_string_tuple(events_path, "EVENT_KINDS"):
            if f"`{kind}`" not in doc and kind not in doc:
                findings.append(Finding(
                    "docs-sync", EVENTS_MODULE, 0,
                    f"event kind '{kind}' is not documented in "
                    f"{OBSERVABILITY_DOC}",
                    hint="add it to the event-kind enumeration",
                ))
    for path, lineno, family in registered_metric_families(ctx):
        if family not in doc:
            findings.append(Finding(
                "docs-sync", ctx.rel(path), lineno,
                f"metric family '{family}' is not documented in "
                f"{OBSERVABILITY_DOC}",
                hint="name the family in the doc's metrics coverage",
            ))
    api_path = ctx.path(HTTP_API_MODULE)
    if api_path.exists():
        for route, lineno in _debug_routes(ctx, api_path):
            if route not in doc:
                findings.append(Finding(
                    "docs-sync", HTTP_API_MODULE, lineno,
                    f"debug endpoint '{route}' is not documented in "
                    f"{OBSERVABILITY_DOC}",
                    hint="add an endpoint row to the doc",
                ))
    return findings


@register_pass(
    "metric-registry",
    "subsystem METRIC_FAMILIES registries vs PrometheusMetrics "
    "declarations, both directions",
)
def run_metric_registry(ctx: RepoContext) -> List[Finding]:
    return metric_registry_findings(ctx)


@register_pass(
    "native-phases",
    "native telemetry PHASES entries each need a declared + registered "
    "native_phase_* family",
)
def run_native_phases(ctx: RepoContext) -> List[Finding]:
    return native_phase_findings(ctx)


@register_pass(
    "debug-sections",
    "/debug/stats served sections vs the DEBUG_STATS_SECTIONS registry, "
    "both directions",
)
def run_debug_sections(ctx: RepoContext) -> List[Finding]:
    return debug_section_findings(ctx)


@register_pass(
    "docs-sync",
    "every event kind, registered metric family and /debug endpoint "
    "must appear in docs/observability.md",
)
def run_docs_sync(ctx: RepoContext) -> List[Finding]:
    return docs_sync_findings(ctx)
