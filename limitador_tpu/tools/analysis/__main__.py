"""CLI for the static-analysis framework.

Usage::

    python -m limitador_tpu.tools.analysis [--all] [paths...]
    python -m limitador_tpu.tools.analysis --list
    python -m limitador_tpu.tools.analysis --only lock-order,style
    python -m limitador_tpu.tools.analysis --json
    python -m limitador_tpu.tools.analysis --write-baseline

Exit codes: 0 clean, 1 active findings, 2 usage error — CI gates on
them (``make lint`` and the tier-1 suite both run ``--all``).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import (
    BASELINE_REL, PASSES, finding_key, load_baseline, repo_root,
    run_passes,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m limitador_tpu.tools.analysis",
        description="pass-registry static analysis (see docs/analysis.md)",
    )
    parser.add_argument(
        "--all", action="store_true",
        help="run every registered pass (the default; spelled out for "
             "CI readability)",
    )
    parser.add_argument(
        "--list", action="store_true", help="list passes and exit",
    )
    parser.add_argument(
        "--only", action="append", default=[],
        help="comma-separated pass names (repeatable)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable output",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="print baseline/allowlist-suppressed findings too",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help=f"write current active findings to {BASELINE_REL}",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline file (report everything)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="override the default lint targets (style/buffer/tracing "
             "file walks)",
    )
    args = parser.parse_args(argv)

    if args.list:
        width = max(len(name) for name in PASSES)
        for name, p in PASSES.items():
            speed = "fast" if p.fast else "slow"
            print(f"{name:<{width}}  [{speed}] {p.description}")
        return 0

    names = []
    for chunk in args.only:
        names.extend(n.strip() for n in chunk.split(",") if n.strip())
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        print(
            f"unknown pass(es): {', '.join(unknown)} "
            f"(use --list)", file=sys.stderr,
        )
        return 2

    root = repo_root()
    for target in args.paths:
        if not Path(target).exists() and not (root / target).exists():
            # a typo'd target silently shrinking the walked set would
            # turn the gate into a false green
            print(f"no such lint target: {target}", file=sys.stderr)
            return 2
    try:
        active, suppressed = run_passes(
            root,
            names=names or None,
            targets=args.paths or None,
            # regeneration must see EVERYTHING, or still-live parked
            # entries (suppressed by the very file being rewritten)
            # would be dropped along with their reasons
            use_baseline=not args.no_baseline and not args.write_baseline,
        )
    except KeyError as exc:  # defensive: unknown name via API
        print(f"unknown pass: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline:
        path = root / BASELINE_REL
        existing = load_baseline(root)
        lines = [
            "# Static-analysis baseline (see docs/analysis.md).",
            "# EMPTY at a healthy HEAD — tests/test_analysis.py asserts "
            "it. Entries",
            "# park known findings during a migration: "
            "'pass|path|message -- reason'.",
        ]
        written = 0
        if names:
            # --only rewrite: entries owned by unselected passes were
            # not re-checked this run — keep them verbatim
            for key, reason in existing.items():
                if key.split("|", 1)[0] not in names:
                    lines.append(f"{key} -- {reason}")
                    written += 1
        for f in active:
            key = finding_key(f)
            reason = existing.get(key, "parked by --write-baseline")
            lines.append(f"{key} -- {reason}")
            written += 1
        path.write_text("\n".join(lines) + "\n")
        print(f"wrote {written} entries to {BASELINE_REL}")
        return 0

    if args.as_json:
        print(json.dumps({
            "passes": names or list(PASSES),
            "active": [f.as_dict() for f in active],
            "suppressed": [f.as_dict() for f in suppressed],
            "baseline_entries": len(load_baseline(root)),
        }, indent=2))
    else:
        for f in active:
            print(f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f.render())
        if active:
            print(f"{len(active)} finding(s)", file=sys.stderr)
        if suppressed:
            print(
                f"{len(suppressed)} suppressed "
                "(--show-suppressed to print)", file=sys.stderr,
            )
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
