"""Lock-order / blocking-in-async analyzer (ISSUE 9 analyzer a).

The hot path spans four threading-lock domains — the storage lock
(``tpu/storage.py`` / ``tpu/sharded.py`` ``_lock``), the native-lane
lock (``_native_lock``), the lease-broker lock (``lease/broker.py``
``_lock``) and the observatory lock (``observability/usage.py``
``_lock``) — plus the plan-cache lock underneath them. The reference
Rust implementation gets ordering safety from the borrow checker; here
the canonical order is a convention::

    broker  ->  native  ->  storage  ->  plan_cache

This pass extracts the actual acquisition graph from the AST (nested
``with`` statements, plus one-level interprocedural propagation through
same-class method calls and package-unique function names) and:

* **rejects cycles** between the named domains — a cycle is a deadlock
  waiting for the right interleaving;
* flags **``await`` while holding a threading lock** — the event loop
  parks the coroutine with the lock held, and every other thread on
  that lock stalls for an unbounded suspension (``asyncio.Lock`` is
  fine to await and is excluded by construction: only attributes
  assigned ``threading.Lock()`` / ``threading.RLock()`` count);
* flags **blocking calls while holding a lock** — ``time.sleep``,
  ``.wait()`` / ``.wait_for()`` on events/conditions, ``.result()`` on
  futures, the blocking ``h2i_take`` ctypes export — outside the
  explicit allowlist below;
* flags the **observatory drain thread's lock holds**: its drain runs
  device kernels under the storage lock by design, so the finding
  exists and is suppressed by an allowlist entry that CITES the
  perf-smoke budget bounding the hold — an explicit contract, not a
  silent pass.

Allowlisted findings are reported with ``suppressed_by`` set (visible
in ``--json`` / ``--show-suppressed``), never dropped.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import Finding, RepoContext, register_pass

__all__ = [
    "TRACKED_DOMAINS", "CANONICAL_ORDER", "ALLOWLIST", "LockAllow",
    "lock_order_findings",
]

#: the named lock domains the acquisition graph is built over.
#: ``peering`` (ISSUE 11) is the pod resilience plane's peer-health
#: lock: it sits on the forwarded-decision path, so it must stay a
#: leaf-ish outermost hold — no sync waits and no storage-plane
#: acquisitions under it.
TRACKED_DOMAINS = (
    "control", "peering", "join", "tier", "broker", "native", "storage",
    "plan_cache", "observatory",
)

#: the documented canonical acquisition order (outermost first); the
#: graph may use any PREFIX-compatible subset, never the reverse.
#: ``control`` (ISSUE 20) is the capacity controller's ring/counter
#: lock: outermost by construction AND leaf in practice — the tick
#: calls every actuator (which take join/broker/storage locks)
#: OUTSIDE it, so it may never be acquired under any other domain.
CANONICAL_ORDER = (
    "control", "peering", "join", "tier", "broker", "native", "storage",
    "plan_cache",
)

#: attribute name -> domain, regardless of receiver (``_native_lock``
#: is unique to the native pipeline)
ATTR_DOMAINS = {
    "_native_lock": "native",
}

#: (module relpath, "self" attr) -> domain for the generically-named
#: ``self._lock`` attributes
MODULE_SELF_DOMAINS = {
    ("limitador_tpu/tpu/storage.py", "_lock"): "storage",
    ("limitador_tpu/tpu/sharded.py", "_lock"): "storage",
    ("limitador_tpu/lease/broker.py", "_lock"): "broker",
    ("limitador_tpu/observability/usage.py", "_lock"): "observatory",
    ("limitador_tpu/tpu/plan_cache.py", "_lock"): "plan_cache",
    ("limitador_tpu/server/peering.py", "_health_lock"): "peering",
    # tiered storage (ISSUE 17): the facade's inherited storage lock
    # guards both tiers; only the migration thread owns the tier lock
    ("limitador_tpu/tier/storage.py", "_lock"): "storage",
    ("limitador_tpu/tier/manager.py", "_lock"): "tier",
    # fast join (ISSUE 18): the membership plane's coordinator lock
    # (resize + join share it — one membership state machine). It is
    # held for state flips only; the ship/migrate RPCs, the kernel
    # warm-up and every admin_call run OUTSIDE it.
    ("limitador_tpu/server/resize.py", "_lock"): "join",
    # capacity controller (ISSUE 20): guards only the decision ring +
    # counters; actuator calls happen outside it (see CANONICAL_ORDER)
    ("limitador_tpu/control/controller.py", "_lock"): "control",
    ("limitador_tpu/control/actuator.py", "_lock"): "control",
}

#: receiver NAME -> domain for cross-object acquisitions
#: (``storage._lock`` / ``self.storage._lock`` from the pipeline,
#: broker and lease modules all mean the device-table lock)
OWNER_NAME_DOMAINS = {
    "storage": "storage",
}

#: blocking call detection while a lock is held: exact dotted names and
#: method-attribute names. Kept deliberately narrow — false positives
#: here train people to allowlist reflexively.
BLOCKING_DOTTED = {"time.sleep"}
BLOCKING_ATTRS = {"wait", "wait_for", "result", "h2i_take"}

#: observatory drain entry points: (module relpath, class, method).
#: Everything their call graph acquires is reported (rule
#: "drain-thread-lock") so a drain that starts holding a NEW lock
#: surfaces immediately.
DRAIN_ENTRY = (
    "limitador_tpu/observability/usage.py",
    "TenantUsageObservatory",
    "drain",
)


@dataclasses.dataclass(frozen=True)
class LockAllow:
    """One explicit allowlist entry: rule + where + the reason the
    pattern is sound (with the budget/test that enforces it)."""

    rule: str       #: "blocking-under-lock" | "drain-thread-lock"
    module: str     #: repo-relative module the finding lands in
    qualname: str   #: enclosing function qualname ("" = any in module)
    needle: str     #: substring of the finding message ("" = any)
    reason: str


ALLOWLIST: Tuple[LockAllow, ...] = (
    # The PR 8 usage-drain-holds-storage-lock pattern: the device top-k
    # drain + slot attribution MUST ride the storage lock (slot
    # identity), and the leased-usage merge MUST ride the native lock
    # (mirror liveness). The hold is bounded, not unbounded: perf-smoke
    # asserts USAGE_DRAIN_BUDGET_MS = 50.0 (tests/test_perf_smoke.py)
    # so the flush path never stalls past one drain pass.
    LockAllow(
        rule="drain-thread-lock",
        module="limitador_tpu/observability/usage.py",
        qualname="TenantUsageObservatory.drain",
        needle="'storage'",
        reason="by design: device top-k + attribution need slot "
               "identity under the storage lock; hold bounded by "
               "USAGE_DRAIN_BUDGET_MS=50.0 (tests/test_perf_smoke.py)",
    ),
    LockAllow(
        rule="drain-thread-lock",
        module="limitador_tpu/observability/usage.py",
        qualname="TenantUsageObservatory.drain",
        needle="'native'",
        reason="by design: leased-usage merge resolves plans under the "
               "native lock; same USAGE_DRAIN_BUDGET_MS=50.0 bound",
    ),
    LockAllow(
        rule="drain-thread-lock",
        module="limitador_tpu/observability/usage.py",
        qualname="TenantUsageObservatory.drain",
        needle="'plan_cache'",
        reason="plan-cache stats/invalidations reached through the "
               "storage hooks; bounded by the same drain budget",
    ),
)


def _allow_reason(
    rule: str, module: str, qualname: str, message: str
) -> Optional[str]:
    for entry in ALLOWLIST:
        if entry.rule != rule or entry.module != module:
            continue
        if entry.qualname and entry.qualname != qualname:
            continue
        if entry.needle and entry.needle not in message:
            continue
        return entry.reason
    return None


# ---------------------------------------------------------------------------
# collection
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FuncInfo:
    module: str
    qualname: str
    name: str
    cls: Optional[str]
    node: ast.AST
    is_async: bool
    #: domains acquired directly in this function's body
    acquires: Set[str] = dataclasses.field(default_factory=set)
    #: (held domain, acquired domain, lineno) direct nesting edges
    edges: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list
    )
    #: (held domains snapshot, callee ref, lineno) calls under a lock
    locked_calls: List[Tuple[Tuple[str, ...], "CallRef", int]] = (
        dataclasses.field(default_factory=list)
    )
    #: (held domains, kind, detail, lineno) direct blocking findings
    blocking: List[Tuple[Tuple[str, ...], str, str, int]] = (
        dataclasses.field(default_factory=list)
    )
    #: every callee referenced anywhere in the body (for closures)
    calls: List["CallRef"] = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class CallRef:
    name: str          #: bare callee name (method or function)
    on_self: bool      #: ``self.name(...)``


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Collector(ast.NodeVisitor):
    """One module walk: find threading-lock attributes per class, then
    per-function acquisition/blocking facts."""

    def __init__(self, ctx: RepoContext, path, rel: str,
                 thread_lock_attrs: Set[str]):
        self.ctx = ctx
        self.path = path
        self.rel = rel
        self.thread_lock_attrs = thread_lock_attrs
        self.funcs: Dict[str, FuncInfo] = {}
        self._cls_stack: List[str] = []
        self._fn_stack: List[FuncInfo] = []
        self._held: List[str] = []

    # -- structure -----------------------------------------------------------

    def visit_ClassDef(self, node):
        self._cls_stack.append(node.name)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _visit_func(self, node, is_async: bool):
        cls = self._cls_stack[-1] if self._cls_stack else None
        qual = f"{cls}.{node.name}" if cls else node.name
        info = FuncInfo(
            module=self.rel, qualname=qual, name=node.name, cls=cls,
            node=node, is_async=is_async,
        )
        # nested defs fold into their parent's qualname slot only if
        # unique; last-in wins is fine for this analysis
        self.funcs[qual] = info
        self._fn_stack.append(info)
        held, self._held = self._held, []
        self.generic_visit(node)
        self._held = held
        self._fn_stack.pop()

    def visit_FunctionDef(self, node):
        self._visit_func(node, False)

    def visit_AsyncFunctionDef(self, node):
        self._visit_func(node, True)

    # -- lock classification -------------------------------------------------

    def _classify(self, expr: ast.AST) -> Optional[str]:
        """Domain name for a with-item context expression, or None when
        it is not a tracked threading lock."""
        if not isinstance(expr, ast.Attribute):
            return None
        attr = expr.attr
        if attr in ATTR_DOMAINS:
            return ATTR_DOMAINS[attr]
        owner = expr.value
        if isinstance(owner, ast.Name):
            if owner.id == "self":
                dom = MODULE_SELF_DOMAINS.get((self.rel, attr))
                if dom:
                    return dom
                if attr in self.thread_lock_attrs:
                    return f"local:{self.rel}:{attr}"
                return None
            if attr == "_lock" and owner.id in OWNER_NAME_DOMAINS:
                return OWNER_NAME_DOMAINS[owner.id]
            return None
        if isinstance(owner, ast.Attribute):
            # self.storage._lock / pipeline.storage._lock
            if attr == "_lock" and owner.attr in OWNER_NAME_DOMAINS:
                return OWNER_NAME_DOMAINS[owner.attr]
        return None

    # -- acquisition ---------------------------------------------------------

    def _enter_with(self, node):
        acquired: List[str] = []
        for item in node.items:
            dom = self._classify(item.context_expr)
            if dom is None:
                continue
            fn = self._fn_stack[-1] if self._fn_stack else None
            if fn is not None:
                fn.acquires.add(dom)
                for held in self._held:
                    if held != dom:
                        fn.edges.append((held, dom, node.lineno))
            acquired.append(dom)
        return acquired

    def visit_With(self, node):
        acquired = self._enter_with(node)
        self._held.extend(acquired)
        self.generic_visit(node)
        for _ in acquired:
            self._held.pop()

    # async-with on a threading lock is nonsensical and would fail at
    # runtime; asyncio locks are untracked — just recurse
    visit_AsyncWith = visit_With

    # -- blocking ------------------------------------------------------------

    def visit_Await(self, node):
        fn = self._fn_stack[-1] if self._fn_stack else None
        if fn is not None and self._held:
            fn.blocking.append(
                (tuple(self._held), "await", "", node.lineno)
            )
        self.generic_visit(node)

    def visit_Call(self, node):
        fn = self._fn_stack[-1] if self._fn_stack else None
        if fn is not None:
            ref = None
            if isinstance(node.func, ast.Name):
                ref = CallRef(node.func.id, False)
            elif isinstance(node.func, ast.Attribute):
                on_self = (
                    isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                )
                ref = CallRef(node.func.attr, on_self)
            if ref is not None:
                fn.calls.append(ref)
                if self._held:
                    fn.locked_calls.append(
                        (tuple(self._held), ref, node.lineno)
                    )
            if self._held:
                dotted = _dotted(node.func)
                attr = (
                    node.func.attr
                    if isinstance(node.func, ast.Attribute) else None
                )
                if dotted in BLOCKING_DOTTED:
                    fn.blocking.append(
                        (tuple(self._held), "call", dotted, node.lineno)
                    )
                elif attr in BLOCKING_ATTRS:
                    # str.join-style false positives don't apply: these
                    # attr names are sync primitives / futures only
                    fn.blocking.append(
                        (tuple(self._held), "call", attr, node.lineno)
                    )
        self.generic_visit(node)


def _thread_lock_attrs(nodes) -> Set[str]:
    """self.<attr> names assigned ``threading.Lock()`` / ``RLock()``
    anywhere in the module (asyncio.Lock is deliberately excluded: it
    is awaited by design)."""
    out: Set[str] = set()
    for node in nodes:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        target = node.targets[0]
        value = node.value
        if not (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and isinstance(value, ast.Call)
        ):
            continue
        dotted = _dotted(value.func)
        if dotted in ("threading.Lock", "threading.RLock"):
            out.add(target.attr)
    return out


# ---------------------------------------------------------------------------
# resolution + graph
# ---------------------------------------------------------------------------

def _closure(
    info: FuncInfo,
    by_class: Dict[Tuple[str, str], FuncInfo],
    by_name: Dict[str, List[FuncInfo]],
    memo: Dict[Tuple[str, str], Set[str]],
    stack: Set[Tuple[str, str]],
    union_ambiguous: bool,
) -> Set[str]:
    """Transitively-acquired domains of ``info``. Callee resolution is
    conservative-by-omission for the EDGE graph (self-calls resolve in
    the same class; other names only when package-unique) and
    conservative-by-union for the drain rule (``union_ambiguous``)."""
    key = (info.module, info.qualname)
    if key in memo:
        return memo[key]
    if key in stack:
        return set(info.acquires)  # recursion: direct only
    stack.add(key)
    out: Set[str] = set(info.acquires)
    for ref in info.calls:
        targets: List[FuncInfo] = []
        if ref.on_self and info.cls is not None:
            hit = by_class.get((info.cls, ref.name))
            if hit is not None:
                targets = [hit]
        if not targets:
            cands = by_name.get(ref.name, [])
            if len(cands) == 1:
                targets = cands
            elif union_ambiguous and 1 < len(cands) <= 8:
                targets = cands
        for t in targets:
            out |= _closure(
                t, by_class, by_name, memo, stack, union_ambiguous
            )
    stack.discard(key)
    memo[key] = out
    return out


def _find_cycles(edges: Dict[str, Set[str]]) -> List[List[str]]:
    cycles: List[List[str]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str], visited: Set[str]):
        for nxt in sorted(edges.get(node, ())):
            if nxt == start:
                cyc = path + [start]
                key = tuple(sorted(cyc[:-1]))
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cyc)
            elif nxt not in visited:
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for start in sorted(edges):
        dfs(start, start, [start], {start})
    return cycles


def lock_order_findings(
    ctx: RepoContext, modules: Optional[Sequence[str]] = None
) -> List[Finding]:
    files = (
        [ctx.path(m) for m in modules] if modules
        else ctx.package_files()
    )
    all_funcs: List[FuncInfo] = []
    for path in files:
        tree = ctx.tree(path)
        if tree is None:
            continue
        rel = ctx.rel(path)
        if rel.startswith("limitador_tpu/tools/"):
            continue  # the analyzers themselves
        collector = _Collector(
            ctx, path, rel, _thread_lock_attrs(ctx.nodes(path))
        )
        collector.visit(tree)
        all_funcs.extend(collector.funcs.values())

    by_class: Dict[Tuple[str, str], FuncInfo] = {}
    by_name: Dict[str, List[FuncInfo]] = {}
    for info in all_funcs:
        if info.cls is not None:
            by_class[(info.cls, info.name)] = info
        by_name.setdefault(info.name, []).append(info)

    findings: List[Finding] = []
    edge_sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
    edges: Dict[str, Set[str]] = {}

    def add_edge(a: str, b: str, module: str, lineno: int) -> None:
        if a == b:
            return
        edges.setdefault(a, set()).add(b)
        edge_sites.setdefault((a, b), (module, lineno))

    memo: Dict[Tuple[str, str], Set[str]] = {}
    for info in all_funcs:
        for held, acquired, lineno in info.edges:
            add_edge(held, acquired, info.module, lineno)
        for held_stack, ref, lineno in info.locked_calls:
            # propagate: calling f while holding L implies L -> every
            # domain f's closure acquires (strict resolution)
            targets: List[FuncInfo] = []
            if ref.on_self and info.cls is not None:
                hit = by_class.get((info.cls, ref.name))
                if hit is not None:
                    targets = [hit]
            else:
                cands = by_name.get(ref.name, [])
                if len(cands) == 1:
                    targets = cands
            for t in targets:
                acq = _closure(t, by_class, by_name, memo, set(), False)
                for dom in acq:
                    for held in held_stack:
                        add_edge(held, dom, info.module, lineno)

    # R1: cycles between tracked domains
    tracked_edges = {
        a: {b for b in bs if b in TRACKED_DOMAINS}
        for a, bs in edges.items() if a in TRACKED_DOMAINS
    }
    for cycle in _find_cycles(tracked_edges):
        first_site = edge_sites.get(
            (cycle[0], cycle[1]), ("limitador_tpu", 0)
        )
        findings.append(Finding(
            "lock-order", first_site[0], first_site[1],
            "lock acquisition cycle: " + " -> ".join(cycle)
            + f" (canonical order is {' -> '.join(CANONICAL_ORDER)})",
            hint="re-nest so every path acquires along the canonical "
                 "order; if a new pairing is needed, re-derive the "
                 "order and update CANONICAL_ORDER + docs/analysis.md",
        ))

    # R1b: tracked edges that invert the canonical order (a cycle
    # waiting for its second half)
    rank = {d: i for i, d in enumerate(CANONICAL_ORDER)}
    for a, bs in sorted(tracked_edges.items()):
        for b in sorted(bs):
            if a in rank and b in rank and rank[a] > rank[b]:
                mod, lineno = edge_sites[(a, b)]
                msg = (
                    f"acquisition edge '{a}' -> '{b}' inverts the "
                    f"canonical order {' -> '.join(CANONICAL_ORDER)}"
                )
                findings.append(Finding(
                    "lock-order", mod, lineno, msg,
                    hint="take the outer lock first or split the "
                         "critical section",
                ))

    # R2/R3: await / blocking calls while holding a threading lock
    for info in all_funcs:
        for held_stack, kind, detail, lineno in info.blocking:
            if ctx.noqa(ctx.path(info.module), lineno):
                continue
            held_desc = ", ".join(f"'{h}'" for h in held_stack)
            if kind == "await":
                msg = (
                    f"await while holding threading lock(s) "
                    f"{held_desc} in {info.qualname}: the coroutine "
                    "parks with the lock held and every thread on it "
                    "stalls for the suspension"
                )
                hint = ("release the lock before awaiting, or make the "
                        "guarded state loop-local")
            else:
                msg = (
                    f"blocking call '{detail}' while holding "
                    f"{held_desc} in {info.qualname}"
                )
                hint = ("move the blocking call outside the critical "
                        "section, or add an explicit LockAllow entry "
                        "citing the budget that bounds the hold")
            reason = _allow_reason(
                "blocking-under-lock", info.module, info.qualname, msg
            )
            findings.append(Finding(
                "lock-order", info.module, lineno, msg, hint=hint,
                suppressed_by=(
                    f"allowlist: {reason}" if reason else None
                ),
            ))

    # R4: the observatory drain thread's lock holds — explicit, never
    # silent. Union-resolution: ambiguous callees (drain_hot_slots is
    # defined per storage flavor) conservatively merge.
    drain_mod, drain_cls, drain_name = DRAIN_ENTRY
    entry = next(
        (f for f in all_funcs
         if f.module == drain_mod and f.cls == drain_cls
         and f.name == drain_name),
        None,
    )
    if entry is not None:
        union_memo: Dict[Tuple[str, str], Set[str]] = {}
        acq = _closure(entry, by_class, by_name, union_memo, set(), True)
        # the observatory's own lock is the drain's to hold; the rule
        # is about the SHARED serving-path locks it reaches out to
        for dom in sorted(acq & set(TRACKED_DOMAINS) - {"observatory"}):
            msg = (
                f"observatory drain thread acquires '{dom}' (via "
                f"{entry.qualname}): the flush path serializes behind "
                "every drain pass"
            )
            reason = _allow_reason(
                "drain-thread-lock", drain_mod, entry.qualname, msg
            )
            findings.append(Finding(
                "lock-order", drain_mod, entry.node.lineno, msg,
                hint="keep the hold inside the perf-smoke drain "
                     "budget, or move the work off the lock",
                suppressed_by=(
                    f"allowlist: {reason}" if reason else None
                ),
            ))
    return findings


@register_pass(
    "lock-order",
    "acquisition-graph cycles, canonical-order inversions, await/"
    "blocking calls under threading locks, drain-thread lock holds",
)
def run(ctx: RepoContext) -> List[Finding]:
    return lock_order_findings(ctx)
