"""ctypes ABI drift pass: C exports vs Python binding declarations.

Every symbol exported from the native sources must have a ctypes
``argtypes`` declaration on the Python side (non-void returns also need
``restype``), and every Python-side declaration must name a symbol that
still exists — a renamed/removed export fails the gate instead of
segfaulting at call time. Ported from ``tools/lint.py`` (PR 5).
"""

from __future__ import annotations

import re
from typing import List

from . import Finding, RepoContext, register_pass

__all__ = [
    "CTYPES_SOURCES", "CTYPES_BINDINGS", "CTYPES_SYMBOL_PREFIXES",
    "exported_c_symbols", "declared_ctypes_signatures", "abi_findings",
]

#: native sources whose extern "C" exports must carry matching ctypes
#: declarations in the binding modules (symbol prefix filters the
#: internal helpers out)
CTYPES_SOURCES = ("native/hostpath.cc", "native/h2ingress.cc")
CTYPES_BINDINGS = (
    "limitador_tpu/native/__init__.py",
    "limitador_tpu/native/ingress.py",
)
CTYPES_SYMBOL_PREFIXES = ("hp_", "h2i_")


def exported_c_symbols(source: str):
    """(name, return_type, has_params) for every exported C function in
    a translation unit (prefix-filtered; extern "C" definitions in this
    repo all sit at column 0 with the return type on the same line)."""
    out = []
    pattern = re.compile(
        r"^([A-Za-z_][A-Za-z0-9_]*\s*\**)\s+("
        + "|".join(p + r"[a-z0-9_]+" for p in CTYPES_SYMBOL_PREFIXES)
        + r")\s*\(([^)]*)",
        re.MULTILINE,
    )
    for match in pattern.finditer(source):
        ret = match.group(1).replace(" ", "")
        name = match.group(2)
        params = match.group(3).strip()
        # multi-line parameter lists never close on the match line; an
        # empty first-line capture with more lines following still means
        # "has params" only when the very next char isn't ')'
        has_params = params not in ("", "void")
        out.append((name, ret, has_params))
    return out


def declared_ctypes_signatures(source: str):
    """{symbol: {"restype", "argtypes"}} assignments in a binding
    module (``lib.<symbol>.restype = ...`` / ``.argtypes = ...``)."""
    out: dict = {}
    for match in re.finditer(
        r"lib\.([A-Za-z_][A-Za-z0-9_]*)\.(restype|argtypes)\s*=", source
    ):
        out.setdefault(match.group(1), set()).add(match.group(2))
    return out


def abi_findings(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    exported: dict = {}
    for rel in CTYPES_SOURCES:
        path = ctx.path(rel)
        if not path.exists():
            continue
        for name, ret, has_params in exported_c_symbols(ctx.source(path)):
            exported[name] = (rel, ret, has_params)
    declared: dict = {}
    for rel in CTYPES_BINDINGS:
        path = ctx.path(rel)
        if not path.exists():
            continue
        for name, kinds in declared_ctypes_signatures(
            ctx.source(path)
        ).items():
            declared.setdefault(name, set()).update(kinds)
    if not exported or not declared:
        return findings
    for name, (rel, ret, has_params) in sorted(exported.items()):
        kinds = declared.get(name)
        if kinds is None:
            findings.append(Finding(
                "ctypes-abi", rel, 0,
                f"exported symbol '{name}' has no ctypes declaration in "
                "the binding modules (drift: a call through the default "
                "int-sized signature corrupts arguments silently)",
                hint="declare lib.<symbol>.argtypes (and restype when "
                     "non-void) in the binding module",
            ))
            continue
        if has_params and "argtypes" not in kinds:
            findings.append(Finding(
                "ctypes-abi", rel, 0,
                f"exported symbol '{name}' takes parameters but the "
                "binding declares no argtypes",
            ))
        if ret != "void" and "restype" not in kinds:
            findings.append(Finding(
                "ctypes-abi", rel, 0,
                f"exported symbol '{name}' returns {ret} but the "
                "binding declares no restype (ctypes truncates to int)",
            ))
    for name in sorted(declared):
        if not name.startswith(CTYPES_SYMBOL_PREFIXES):
            continue
        if name not in exported:
            findings.append(Finding(
                "ctypes-abi", "limitador_tpu/native", 0,
                f"binding declares '{name}' but no native source "
                "exports it (renamed or removed symbol)",
                hint="rename the binding to match the export, or drop "
                     "the dead declaration",
            ))
    return findings


@register_pass(
    "ctypes-abi",
    "native extern-C exports vs ctypes argtypes/restype declarations, "
    "both directions",
)
def run(ctx: RepoContext) -> List[Finding]:
    return abi_findings(ctx)
