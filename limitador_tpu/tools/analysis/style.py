"""Style pass: the per-file AST linter (pyflakes/clippy classes).

Ported verbatim from the original ``tools/lint.py`` gate — syntax
errors, unused/redefined imports, bare ``except:``, mutable default
arguments, ``==``/``!=`` against True/False/None, duplicate dict keys,
tabs in indentation and trailing whitespace. ``# noqa`` anywhere on the
offending line suppresses that finding.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Tuple

from . import Finding, RepoContext, register_pass

__all__ = ["lint_file", "lint_paths", "run"]


def _imported_bindings(tree: ast.AST):
    """(lineno, bound_name, scope_id) for every import; scope_id keys
    the nearest enclosing function/class/module, so a deliberate lazy
    re-import inside a function never collides with the module scope
    (pyflakes F811 is same-scope only too)."""
    out = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.scope = [id(tree)]

        def visit_Import(self, node):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                # redef key keeps the dotted path: `import urllib.request`
                # and `import urllib.error` both bind 'urllib' on purpose
                out.append(
                    (node.lineno, bound, alias.name, self.scope[-1])
                )

        def visit_ImportFrom(self, node):
            if node.module == "__future__":
                return  # compiler directive, not a binding
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name
                out.append(
                    (node.lineno, bound, bound, self.scope[-1])
                )

        def _scoped(self, node):
            self.scope.append(id(node))
            self.generic_visit(node)
            self.scope.pop()

        visit_FunctionDef = _scoped
        visit_AsyncFunctionDef = _scoped
        visit_ClassDef = _scoped
        visit_Lambda = _scoped

    V().visit(tree)
    return out


def _used_names(tree: ast.AST, nodes=None):
    used = set()
    for node in (nodes if nodes is not None else ast.walk(tree)):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # "a.b.c" usage roots at the Name, already collected
            pass
        elif isinstance(node, ast.Assign):
            for target in node.targets:
                if (
                    isinstance(target, ast.Name)
                    and target.id == "__all__"
                    and isinstance(node.value, (ast.List, ast.Tuple))
                ):
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            used.add(elt.value)
    return used


def lint_file(path: Path) -> List[Tuple[int, str]]:
    """(lineno, message) findings for one file — the legacy per-file
    entry point ``tests/test_lint.py`` rides."""
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as exc:
        return [(exc.lineno or 0, f"syntax error: {exc.msg}")]
    return _lint_source(src, tree)


def _lint_source(src: str, tree: ast.AST, nodes=None) -> List[Tuple[int, str]]:
    lines = src.splitlines()

    def suppressed(lineno: int) -> bool:
        return (
            0 < lineno <= len(lines) and "# noqa" in lines[lineno - 1]
        )

    findings: List[Tuple[int, str]] = []

    # unused + same-scope-redefined imports
    bindings = _imported_bindings(tree)
    used = _used_names(tree, nodes)
    seen: dict = {}
    for lineno, name, full, scope in bindings:
        key = (full, scope)
        if key in seen and not suppressed(lineno):
            findings.append(
                (lineno, f"import '{name}' redefines line {seen[key]}")
            )
        seen.setdefault(key, lineno)
    for lineno, name, _full, _scope in bindings:
        if name not in used and not suppressed(lineno):
            findings.append((lineno, f"unused import '{name}'"))

    for node in (nodes if nodes is not None else ast.walk(tree)):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            if not suppressed(node.lineno):
                findings.append(
                    (node.lineno, "bare 'except:' swallows everything")
                )
        elif isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            for default in (
                list(node.args.defaults) + list(node.args.kw_defaults)
            ):
                if isinstance(
                    default, (ast.List, ast.Dict, ast.Set)
                ) and not suppressed(default.lineno):
                    findings.append((
                        default.lineno,
                        f"mutable default argument in '{node.name}'",
                    ))
        elif isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if (
                    isinstance(op, (ast.Eq, ast.NotEq))
                    and isinstance(comp, ast.Constant)
                    and (comp.value is None or comp.value is True
                         or comp.value is False)
                    and not suppressed(node.lineno)
                ):
                    findings.append((
                        node.lineno,
                        f"comparison to {comp.value!r} with ==/!= "
                        "(use is/is not or truthiness)",
                    ))
        elif isinstance(node, ast.Dict):
            keys = [
                k.value
                for k in node.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, (str, int))
            ]
            dupes = {k for k in keys if keys.count(k) > 1}
            if dupes and not suppressed(node.lineno):
                findings.append((
                    node.lineno,
                    f"duplicate dict keys: {sorted(map(repr, dupes))}",
                ))

    for i, line in enumerate(lines, 1):
        if "# noqa" in line:
            continue
        stripped = line.rstrip("\n")
        if stripped != stripped.rstrip():
            findings.append((i, "trailing whitespace"))
        indent = stripped[: len(stripped) - len(stripped.lstrip())]
        if "\t" in indent:
            findings.append((i, "tab in indentation"))

    return sorted(findings)


def lint_paths(targets) -> List[str]:
    """Legacy string-rendered findings over explicit targets."""
    out = []
    files = []
    for target in targets:
        p = Path(target)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    files = [f for f in files if not f.name.endswith("_pb2.py")
             and not f.name.endswith("_pb2_grpc.py")]
    for f in files:
        for lineno, msg in lint_file(f):
            out.append(f"{f}:{lineno}: {msg}")
    return out


@register_pass(
    "style",
    "per-file AST lint: syntax, imports, bare except, mutable defaults, "
    "True/None comparisons, duplicate keys, whitespace",
)
def run(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    for path in ctx.iter_files():
        rel = ctx.rel(path)
        tree = ctx.tree(path)  # shared parse cache across passes
        if tree is None:
            src = ctx.source(path)
            try:
                ast.parse(src, filename=str(path))
            except SyntaxError as exc:
                findings.append(Finding(
                    "style", rel, exc.lineno or 0,
                    f"syntax error: {exc.msg}",
                ))
            continue
        for lineno, msg in _lint_source(
            ctx.source(path), tree, ctx.nodes(path)
        ):
            findings.append(Finding("style", rel, lineno, msg))
    return findings
