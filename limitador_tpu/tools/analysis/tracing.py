"""Tracing-safety pass for the hot-path modules (ISSUE 9 analyzer c).

One stray host sync or recompile on the decision path blows the 2ms p99
budget (BASELINE north star). Four rules, each an incident class this
repo has already paid for:

* **no-host-sync** — ``block_until_ready`` / ``jax.device_get`` in a
  hot-path module: the decision path must stay async against the
  device; syncs belong to bench/warmup code.
* **no-implicit-asarray** — ``np.asarray(x)`` / ``np.array(x)``
  WITHOUT a dtype inside a decision-path function: with a device array
  argument that is a silent blocking device->host transfer per batch.
  Host staging always knows its dtype (``np.asarray(x, np.int32)``);
  spelling it keeps the conversion provably host-side and self-
  documents the intent.
* **kernel-launch-locality** — calls into ``ops/kernel.py`` functions
  from modules OUTSIDE the quantizing owners (storage/sharded/
  replicated/mesh): the owners pad every jit-visible shape to the pow2
  hit buckets; a direct launch from anywhere else ships un-quantized
  shapes and recompiles per batch size (measured 300ms+ stalls,
  PR 4). Reading kernel CONSTANTS (``K.MAX_DELTA_CAP``) is fine — only
  calls are flagged.
* **shard-map-donation** — generalizing the donation pass: a
  ``shard_map``/``_shard_map`` site whose wrapped kernel carries the
  counter table must sit inside a function that is itself a donating
  table kernel (the donation pass checks its jit site) — otherwise the
  per-shard table copies come back through the side door.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from . import Finding, RepoContext, register_pass
from .donation import DONATION_CHECKED_MODULES, DONATION_PARAMS

__all__ = [
    "HOT_MODULES", "DECISION_PREFIXES", "KERNEL_OWNER_MODULES",
    "tracing_findings",
]

#: modules on the serving hot path (decision-path rules apply here).
#: routing.py and the peer-forwarding lane joined with the pod tier
#: (ISSUE 10): every decision consults the router, and a forwarded
#: descriptor's whole latency budget is the peering module — a host
#: sync or implicit asarray smuggled into either would tax ALL pod
#: traffic.
HOT_MODULES = (
    "limitador_tpu/tpu/native_pipeline.py",
    "limitador_tpu/tpu/storage.py",
    "limitador_tpu/tpu/sharded.py",
    "limitador_tpu/tpu/batcher.py",
    "limitador_tpu/tpu/plan_cache.py",
    "limitador_tpu/tpu/pipeline.py",
    "limitador_tpu/native/ingress.py",
    "limitador_tpu/routing.py",
    "limitador_tpu/server/peering.py",
    # pod observability plane (ISSUE 12): hop recording runs per
    # forwarded decision and event emission inside the resilience
    # paths — aggregation must stay off the decision path, so the
    # no-sync/no-implicit-asarray rules watch these modules too.
    "limitador_tpu/observability/pod_plane.py",
    "limitador_tpu/observability/events.py",
    # pod fast path (ISSUE 13): the lockstep psum lane's decision
    # surface (check_and_update/is_rate_limited/update_counters) is
    # sync and lock-cheap by contract — never an RPC, never a device
    # sync; the exchange round alone owns the collective transport.
    "limitador_tpu/parallel/mesh.py",
    # serving-model observatory (ISSUE 14): ingest() rides every
    # batch collect — lock + bounded append ONLY; the refit, probe
    # and forecast belong to the observatory drain thread, and a
    # sync/launch smuggled into the module would tax every flush.
    "limitador_tpu/observability/model.py",
    # elastic pod (ISSUE 15): the coordinator's decision-path surface
    # is the epoch check the lane runs per forward (one int compare
    # per payload); migration/abort work lives on its own threads and
    # must never be named with a decision prefix.
    "limitador_tpu/server/resize.py",
    # tiered storage (ISSUE 17): cold-tier decides ride the big-limit
    # host lane per batch (is_within_limits/_eval_big_hits overrides),
    # so the no-sync/no-implicit-asarray rules apply; migration work
    # belongs to the TierManager thread and must never be named with a
    # decision prefix. Device access goes through the TpuStorage
    # peek/seed helpers — tier/ is NOT a kernel owner.
    "limitador_tpu/tier/storage.py",
    "limitador_tpu/tier/manager.py",
    # fast join (ISSUE 18): the joiner's decision-path surface is one
    # attribute read per forwarded decision (the ttfd stamp hook);
    # warm-up and the state ship run at boot / on the join driver
    # thread and must never be named with a decision prefix.
    "limitador_tpu/server/standby.py",
    # capacity controller (ISSUE 20): knob writes land on subsystem
    # hot paths (the limiter cap, the planner target, the broker
    # scale) and signal_fields() rides every bus snapshot — no sync,
    # no launch, no implicit asarray may live here; the cadence tick
    # itself runs on the controller's own thread.
    "limitador_tpu/control/controller.py",
    "limitador_tpu/control/actuator.py",
)

#: function-name prefixes that mark the decision path (begin/submit
#: side — the finish side owns the device sync by definition).
#: ``forward``/``_forward``/``_remote``/``_degraded`` joined with the
#: pod resilience plane (ISSUE 11): a forwarded or failed-over
#: decision's whole latency budget runs through them.
#: ``check_and_update``/``is_rate_limited``/``update_counters`` joined
#: with the pod psum lane (ISSUE 13): its whole point is a local-only
#: decision, so a sync or RPC smuggled into it defeats the lane.
DECISION_PREFIXES = (
    "decide", "submit", "begin_", "_begin", "pad_hits",
    "forward", "_forward", "_remote", "_degraded",
    "check_and_update", "is_rate_limited", "update_counters",
)

#: modules allowed to call ops/kernel.py functions: they own the pow2
#: bucket quantization of every jit-visible shape
KERNEL_OWNER_MODULES = (
    "limitador_tpu/ops/kernel.py",
    "limitador_tpu/tpu/storage.py",
    "limitador_tpu/tpu/sharded.py",
    "limitador_tpu/tpu/replicated.py",
    "limitador_tpu/parallel/mesh.py",
    # warm standby (ISSUE 18): warm-up intentionally drives the jitted
    # kernels at every pow2 hit bucket (all-padding batches against a
    # scratch table) so the serving path never pays the compile
    "limitador_tpu/server/standby.py",
)

KERNEL_MODULE = "limitador_tpu/ops/kernel.py"


def _kernel_function_names(ctx: RepoContext) -> Set[str]:
    path = ctx.path(KERNEL_MODULE)
    if ctx.tree(path) is None:
        return set()
    return {
        node.name for node in ctx.nodes(path)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and not node.name.startswith("_")
    }


def _kernel_aliases(nodes) -> Set[str]:
    """Names the module binds to ops.kernel (``from ..ops import kernel
    as K`` / ``import ...ops.kernel as kernel``)."""
    out: Set[str] = set()
    for node in nodes:
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.endswith("ops") or node.module.endswith("ops.kernel")
        ):
            for alias in node.names:
                if alias.name == "kernel" or node.module.endswith(
                    "ops.kernel"
                ):
                    out.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith("ops.kernel"):
                    out.add(alias.asname or alias.name.split(".")[0])
    return out


def _enclosing_function(
    tree: ast.AST, target: ast.AST
) -> Optional[ast.FunctionDef]:
    """Innermost FunctionDef lexically containing ``target``."""
    best: Optional[ast.FunctionDef] = None

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack: List[ast.FunctionDef] = []

        def generic_visit(self, node):
            nonlocal best
            if node is target and self.stack:
                best = self.stack[-1]
            is_fn = isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            )
            if is_fn:
                self.stack.append(node)
            super().generic_visit(node)
            if is_fn:
                self.stack.pop()

    V().visit(tree)
    return best


def tracing_findings(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    kernel_fns = _kernel_function_names(ctx)

    # -- rules 1-2: host syncs in hot modules --------------------------------
    for rel in HOT_MODULES:
        path = ctx.path(rel)
        tree = ctx.tree(path)
        if tree is None:
            continue

        decision_spans: List[tuple] = []
        for node in ctx.nodes(path):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node.name.startswith(DECISION_PREFIXES):
                end = getattr(node, "end_lineno", node.lineno)
                decision_spans.append((node.lineno, end, node.name))

        def decision_fn(lineno: int) -> Optional[str]:
            for lo, hi, name in decision_spans:
                if lo <= lineno <= hi:
                    return name
            return None

        for node in ctx.nodes(path):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            attr = fn.attr if isinstance(fn, ast.Attribute) else None
            if attr == "block_until_ready" and not ctx.noqa(
                path, node.lineno
            ):
                findings.append(Finding(
                    "tracing-safety", rel, node.lineno,
                    "block_until_ready in a hot-path module: the "
                    "decision path must stay async against the device",
                    hint="move the sync to bench/warmup code, or # noqa "
                         "with the reason if this is a warmup helper",
                ))
                continue
            if (
                attr == "device_get"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "jax"
            ):
                where = decision_fn(node.lineno)
                if where and not ctx.noqa(path, node.lineno):
                    findings.append(Finding(
                        "tracing-safety", rel, node.lineno,
                        f"jax.device_get on the decision path "
                        f"('{where}'): blocking device->host transfer "
                        "per batch",
                        hint="defer the transfer to the finish side",
                    ))
                continue
            if (
                attr in ("asarray", "array")
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "np"
            ):
                where = decision_fn(node.lineno)
                if where is None:
                    continue
                has_dtype = len(node.args) >= 2 or any(
                    k.arg == "dtype" for k in node.keywords
                )
                if not has_dtype and not ctx.noqa(path, node.lineno):
                    findings.append(Finding(
                        "tracing-safety", rel, node.lineno,
                        f"implicit np.{attr}(x) on the decision path "
                        f"('{where}'): with a device array this is a "
                        "silent blocking transfer",
                        hint="spell the dtype (np.asarray(x, np.int32)) "
                             "to keep the conversion provably host-side",
                    ))

    # -- rule 3: kernel-launch locality --------------------------------------
    if kernel_fns:
        for path in ctx.package_files():
            rel = ctx.rel(path)
            if rel in KERNEL_OWNER_MODULES or rel.startswith(
                "limitador_tpu/tools/"
            ):
                continue
            if ctx.tree(path) is None:
                continue
            aliases = _kernel_aliases(ctx.nodes(path))
            if not aliases:
                continue
            for node in ctx.nodes(path):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in aliases
                    and node.func.attr in kernel_fns
                ):
                    continue
                if ctx.noqa(path, node.lineno):
                    continue
                findings.append(Finding(
                    "tracing-safety", rel, node.lineno,
                    f"direct kernel launch '{node.func.attr}' outside "
                    "the quantizing owner modules: jit-visible shapes "
                    "must be padded to the pow2 hit buckets or every "
                    "batch size compiles a new XLA program",
                    hint="route the launch through TpuStorage/"
                         "TpuShardedStorage (they own pad_hits and the "
                         "bucket quantization)",
                ))

    # -- rule 4: shard_map sites donation-checked ----------------------------
    for rel in DONATION_CHECKED_MODULES:
        path = ctx.path(rel)
        tree = ctx.tree(path)
        if tree is None:
            continue
        funcs = {
            node.name: node for node in ctx.nodes(path)
            if isinstance(node, ast.FunctionDef)
        }
        for node in ctx.nodes(path):
            if not (
                isinstance(node, ast.Call)
                and (
                    (isinstance(node.func, ast.Name)
                     and node.func.id in ("shard_map", "_shard_map"))
                    or (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "shard_map")
                )
                and node.args
                and isinstance(node.args[0], ast.Name)
            ):
                continue
            host = _enclosing_function(tree, node)
            if host is not None and node.args[0].id in {
                a.arg for a in host.args.args
            }:
                # pass-through helper (e.g. the version-compat
                # _shard_map wrapper): the REAL site is the caller,
                # checked on its own visit
                continue
            wrapped = None
            if host is not None:
                # prefer the kernel nested in the calling function —
                # the sharded launchers all use a local `def fn(...)`
                wrapped = next(
                    (n for n in ast.walk(host)
                     if isinstance(n, ast.FunctionDef)
                     and n.name == node.args[0].id and n is not host),
                    None,
                )
            if wrapped is None:
                wrapped = funcs.get(node.args[0].id)
            if wrapped is None:
                continue
            w_params = {a.arg for a in wrapped.args.args} & DONATION_PARAMS
            if not w_params:
                continue
            host_params = (
                {a.arg for a in host.args.args} & DONATION_PARAMS
                if host is not None else set()
            )
            if host is None or not host_params:
                if not ctx.noqa(path, node.lineno):
                    findings.append(Finding(
                        "tracing-safety", rel, node.lineno,
                        f"shard_map over table-carrying kernel "
                        f"'{node.args[0].id}' is not enclosed in a "
                        "table-carrying function the donation pass can "
                        "check: per-shard table copies come back "
                        "through the side door",
                        hint="thread the table params through the "
                             "enclosing function so its jit site is "
                             "donation-checked",
                    ))
    return findings


@register_pass(
    "tracing-safety",
    "no host syncs / implicit asarray on the decision path, kernel "
    "launches only from pow2-quantizing owners, shard_map donation",
)
def run(ctx: RepoContext) -> List[Finding]:
    return tracing_findings(ctx)
