"""Pass-registry static-analysis framework (ISSUE 9).

The reference gets data-race freedom and API-misuse checks from the
Rust compiler; this stack spans three concurrency domains with no
compiler help — relaxed-atomics C++ in ``native/``, multi-loop async
Python, and donated JAX kernels where one stray host sync blows the 2ms
p99 budget. This package is the correctness tooling that earns the
equivalent: the five ad-hoc passes that used to live in
``tools/lint.py`` (style, metric-registry, donation, ctypes-ABI drift,
native-phase / debug-section cross-checks) ported onto one registry,
plus the analyzers the hot path actually needs:

* ``lock-order`` — the acquisition graph over the storage lock,
  native-lane lock, broker lock and observatory lock, extracted from
  the AST: cycles are rejected, ``await``/blocking calls while holding
  a threading lock are flagged, and the observatory drain thread's
  storage-lock hold is allowlisted EXPLICITLY (citing its perf-smoke
  budget), not silently passed.
* ``buffer-safety`` — ctypes calls into the GIL-released ``hp_*`` /
  ``h2i_*`` exports whose numpy buffer arguments are temporaries that
  die before the call returns.
* ``tracing-safety`` — hot-path modules must not host-sync on the
  decision path (``block_until_ready``, implicit ``np.asarray``),
  kernel launches must ride the pow2-quantizing owner modules, and
  ``shard_map`` sites are donation-checked.

Model: each pass is a function ``run(ctx) -> List[Finding]`` registered
under a name. ``python -m limitador_tpu.tools.analysis`` runs them all
(``--list`` / ``--only`` / ``--json`` for CI), exit 1 on any active
finding. ``baseline.txt`` (checked in, EMPTY at HEAD) suppresses known
findings during a migration without losing them — suppressed findings
stay visible in ``--json`` and ``--show-suppressed``. ``# noqa`` on the
offending line suppresses single style findings, as before.

``tools/lint.py`` remains as a thin compatibility shim over this
package, so ``make lint``, ``tests/test_lint.py`` and every docstring
that says "tools/lint.py" keep working.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "AnalysisPass",
    "PASSES",
    "RepoContext",
    "register_pass",
    "run_passes",
    "load_baseline",
    "finding_key",
    "repo_root",
    "DEFAULT_TARGETS",
    "BASELINE_REL",
]

DEFAULT_TARGETS = ("limitador_tpu", "tests", "bench.py",
                   "__graft_entry__.py")

#: the checked-in baseline/suppression file, repo-relative. Empty at
#: HEAD (tests/test_analysis.py asserts it): a finding lands here only
#: while a migration is in flight, with a dated comment saying why.
BASELINE_REL = "limitador_tpu/tools/analysis/baseline.txt"


@dataclasses.dataclass
class Finding:
    """One analyzer finding: where, what, and how to fix it."""

    pass_name: str
    path: str       #: repo-relative posix path (absolute when outside)
    line: int
    message: str
    hint: str = ""
    #: set when a baseline entry or a pass allowlist suppressed it —
    #: carries the reason, so a suppression is never silent
    suppressed_by: Optional[str] = None

    def render(self) -> str:
        out = f"{self.path}:{self.line}: [{self.pass_name}] {self.message}"
        if self.hint:
            out += f"\n    fix: {self.hint}"
        if self.suppressed_by:
            out += f"\n    suppressed: {self.suppressed_by}"
        return out

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def finding_key(finding: Finding) -> str:
    """Baseline key: line-number-insensitive so unrelated edits above a
    baselined finding don't resurrect it."""
    return f"{finding.pass_name}|{finding.path}|{finding.message}"


@dataclasses.dataclass(frozen=True)
class AnalysisPass:
    name: str
    description: str
    run: Callable[["RepoContext"], List[Finding]]
    #: fast passes ride tier-1 (the <10s perf-smoke budget); slow ones
    #: (none today — the sanitizer race hunt lives in pytest) only run
    #: with --all-slow
    fast: bool = True


#: name -> pass, in registration order (determines run + report order)
PASSES: Dict[str, AnalysisPass] = {}


def register_pass(name: str, description: str, fast: bool = True):
    def wrap(fn):
        PASSES[name] = AnalysisPass(name, description, fn, fast)
        return fn
    return wrap


def repo_root() -> Path:
    return Path(__file__).resolve().parents[3]


class RepoContext:
    """Shared walkers for every pass: one parse per file per run, repo-
    relative paths, target iteration and ``# noqa`` suppression."""

    def __init__(self, root, targets: Optional[Sequence] = None):
        self.root = Path(root).resolve()
        self.targets = tuple(str(t) for t in (targets or DEFAULT_TARGETS))
        self._sources: Dict[Path, str] = {}
        self._trees: Dict[Path, Optional[ast.AST]] = {}
        self._nodes: Dict[Path, List[ast.AST]] = {}
        self._files: Optional[List[Path]] = None

    # -- paths ---------------------------------------------------------------

    def rel(self, path) -> str:
        path = Path(path)
        try:
            return path.resolve().relative_to(self.root).as_posix()
        except ValueError:
            return str(path)

    def path(self, rel: str) -> Path:
        return self.root / rel

    # -- cached reads --------------------------------------------------------

    def source(self, path) -> str:
        path = Path(path)
        if path not in self._sources:
            try:
                self._sources[path] = path.read_text()
            except OSError:
                self._sources[path] = ""
        return self._sources[path]

    def lines(self, path) -> List[str]:
        return self.source(path).splitlines()

    def tree(self, path) -> Optional[ast.AST]:
        """Parsed AST, or None on syntax error / missing file (the
        style pass reports syntax errors; every other pass skips)."""
        path = Path(path)
        if path not in self._trees:
            src = self.source(path)
            try:
                self._trees[path] = ast.parse(src, filename=str(path))
            except SyntaxError:
                self._trees[path] = None
        return self._trees[path]

    def nodes(self, path) -> List[ast.AST]:
        """Flattened node list of ``tree(path)``, cached — ``ast.walk``
        re-traverses the tree per call, and with nine passes over the
        same files the traversal dominates the gate's runtime."""
        path = Path(path)
        if path not in self._nodes:
            tree = self.tree(path)
            self._nodes[path] = [] if tree is None else list(ast.walk(tree))
        return self._nodes[path]

    def noqa(self, path, lineno: int) -> bool:
        lines = self.lines(path)
        return 0 < lineno <= len(lines) and "# noqa" in lines[lineno - 1]

    # -- iteration -----------------------------------------------------------

    def iter_files(self) -> List[Path]:
        """The lintable target set (style/buffer/tracing walk this);
        generated protobuf output is excluded — protoc's style, not
        ours."""
        if self._files is None:
            files: List[Path] = []
            for target in self.targets:
                p = Path(target)
                if not p.is_absolute():
                    p = self.root / target
                if p.is_dir():
                    files.extend(sorted(p.rglob("*.py")))
                elif p.suffix == ".py" and p.exists():
                    files.append(p)
            self._files = [
                f for f in files
                if not f.name.endswith("_pb2.py")
                and not f.name.endswith("_pb2_grpc.py")
            ]
        return self._files

    def package_files(self, rel_prefix: str = "limitador_tpu") -> List[Path]:
        pkg = self.root / rel_prefix
        if not pkg.is_dir():
            return []
        return [
            f for f in sorted(pkg.rglob("*.py"))
            if not f.name.endswith("_pb2.py")
            and not f.name.endswith("_pb2_grpc.py")
        ]

    # -- shared AST helpers ---------------------------------------------------

    def module_string_tuple(self, path, name: str) -> List[str]:
        """Entries of a module-level ``NAME = ("a", "b", ...)``
        tuple/list assignment (string constants only)."""
        tree = self.tree(path)
        if tree is None:
            return []
        out: List[str] = []
        for node in tree.body:
            if not (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == name
                    for t in node.targets
                )
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                continue
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    out.append(elt.value)
        return out


# -- baseline ----------------------------------------------------------------

def load_baseline(root: Path) -> Dict[str, str]:
    """key -> reason from the checked-in baseline file. Format: one
    finding key per line (``pass|path|message``), ``#`` comments; a
    trailing `` -- reason`` documents why it's parked."""
    path = Path(root) / BASELINE_REL
    out: Dict[str, str] = {}
    try:
        text = path.read_text()
    except OSError:
        return out
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        key, _sep, reason = line.partition(" -- ")
        out[key.strip()] = reason.strip() or "baselined"
    return out


def run_passes(
    root=None,
    names: Optional[Sequence[str]] = None,
    targets: Optional[Sequence] = None,
    use_baseline: bool = True,
) -> Tuple[List[Finding], List[Finding]]:
    """Run the selected passes (all registered when ``names`` is None)
    and split findings into (active, suppressed). Unknown pass names
    raise KeyError — the CLI maps that to exit 2."""
    root = Path(root) if root is not None else repo_root()
    ctx = RepoContext(root, targets)
    selected = list(names) if names else list(PASSES)
    findings: List[Finding] = []
    for name in selected:
        findings.extend(PASSES[name].run(ctx))
    baseline = load_baseline(root) if use_baseline else {}
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        if f.suppressed_by is None and baseline:
            reason = baseline.get(finding_key(f))
            if reason is not None:
                f.suppressed_by = f"baseline: {reason}"
        (suppressed if f.suppressed_by else active).append(f)
    return active, suppressed


# Pass modules register themselves on import; order here is report
# order (cheap structural passes first, the graph analyzers last).
from . import style           # noqa: E402  (registration import)
from . import registries      # noqa: E402
from . import donation        # noqa: E402
from . import native_abi      # noqa: E402
from . import buffer_safety   # noqa: E402
from . import lock_order      # noqa: E402
from . import tracing         # noqa: E402
