"""Buffer-donation pass: table-carrying ``jax.jit`` sites must donate.

Without ``donate_argnums`` XLA copies the whole counter table on every
launch instead of updating it in place — 8 bytes/slot/batch of silent
HBM traffic. Ported from ``tools/lint.py`` (PR 4); the ``shard_map``
half of the check (every shard-mapped table kernel must sit inside a
donating jit) lives in the tracing-safety pass, which generalizes this
one.
"""

from __future__ import annotations

import ast
from typing import List

from . import Finding, RepoContext, register_pass

__all__ = [
    "DONATION_CHECKED_MODULES", "DONATION_PARAMS", "DONATION_EXEMPT",
    "donation_findings", "is_jax_jit",
]

#: modules whose jax.jit sites must donate table-carrying buffers
DONATION_CHECKED_MODULES = (
    "limitador_tpu/ops/kernel.py",
    "limitador_tpu/parallel/mesh.py",
    "limitador_tpu/tpu/replicated.py",
)

#: table parameter names that mark a kernel as table-carrying ("hits"
#: is the per-slot traffic accumulator column — same in-place contract)
DONATION_PARAMS = frozenset({"state", "values", "expiry", "hits"})

#: read-only kernels: they take the table but never produce a new one,
#: so there is nothing to update in place
DONATION_EXEMPT = frozenset({"read_slots"})


def is_jax_jit(node) -> bool:
    return (
        isinstance(node, ast.Attribute) and node.attr == "jit"
        and isinstance(node.value, ast.Name) and node.value.id == "jax"
    )


def donation_findings(ctx: RepoContext) -> List[Finding]:
    """Covers the three site shapes the kernels use — ``@jax.jit``,
    ``@functools.partial(jax.jit, ...)`` and
    ``functools.partial(jax.jit, ...)(fn)`` — and allowlists the
    read-only kernels (DONATION_EXEMPT)."""
    findings: List[Finding] = []
    for rel in DONATION_CHECKED_MODULES:
        path = ctx.path(rel)
        if not path.exists():
            continue
        tree = ctx.tree(path)
        if tree is None:
            continue  # reported by the style pass
        funcs = {
            node.name: node
            for node in ctx.nodes(path)
            if isinstance(node, ast.FunctionDef)
        }

        def check(lineno: int, kwargs, fn_name: str) -> None:
            fn_node = funcs.get(fn_name)
            if fn_node is None or fn_name in DONATION_EXEMPT:
                return
            params = sorted(
                {a.arg for a in fn_node.args.args} & DONATION_PARAMS
            )
            if not params or "donate_argnums" in kwargs:
                return
            if ctx.noqa(path, lineno):
                return
            findings.append(Finding(
                "donation", ctx.rel(path), lineno,
                f"jax.jit site for table-carrying kernel '{fn_name}' "
                f"(params {params}) passes no donate_argnums — every "
                "launch would copy the counter table instead of "
                "updating it in place",
                hint="pass donate_argnums covering the table params, "
                     "or add the kernel to DONATION_EXEMPT if it is "
                     "read-only",
            ))

        for node in ctx.nodes(path):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    if is_jax_jit(dec):
                        check(dec.lineno, set(), node.name)
                    elif isinstance(dec, ast.Call):
                        kwargs = {k.arg for k in dec.keywords}
                        if is_jax_jit(dec.func):
                            check(dec.lineno, kwargs, node.name)
                        elif (
                            isinstance(dec.func, ast.Attribute)
                            and dec.func.attr == "partial"
                            and dec.args and is_jax_jit(dec.args[0])
                        ):
                            check(dec.lineno, kwargs, node.name)
            elif isinstance(node, ast.Call):
                func = node.func
                wrapped = (
                    node.args[0].id
                    if node.args and isinstance(node.args[0], ast.Name)
                    else None
                )
                if wrapped is None:
                    continue
                if (
                    isinstance(func, ast.Call)
                    and isinstance(func.func, ast.Attribute)
                    and func.func.attr == "partial"
                    and func.args and is_jax_jit(func.args[0])
                ):
                    # functools.partial(jax.jit, ...)(fn)
                    check(
                        node.lineno, {k.arg for k in func.keywords}, wrapped
                    )
                elif is_jax_jit(func):
                    # jax.jit(fn, ...)
                    check(
                        node.lineno, {k.arg for k in node.keywords}, wrapped
                    )
    return findings


@register_pass(
    "donation",
    "table-carrying jax.jit kernels must pass donate_argnums "
    "(read-only kernels exempt)",
)
def run(ctx: RepoContext) -> List[Finding]:
    return donation_findings(ctx)
