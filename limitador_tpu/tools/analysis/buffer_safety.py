"""GIL-release buffer-safety pass (ISSUE 9 analyzer b).

ctypes releases the GIL around every call into the native libraries, so
the C side reads its pointer arguments while Python is free to run — a
buffer must stay referenced from Python for the WHOLE call. The classic
bug: ``lib.hp_tel_drain(np.empty(n).ctypes.data, n)``. ``.ctypes.data``
extracts a raw integer address; the temporary array's refcount hits
zero the moment the argument expression finishes evaluating — BEFORE
the C call runs — and the allocator is free to reuse the memory under
the GIL-released call. The same holds for ``.ctypes.data_as(...)`` on
temporaries and for pointer extraction from ``x.astype(...)`` /
``x.copy()`` / ``np.ascontiguousarray(x)`` results.

What is safe, and why the pass allows it:

* ``buf.ctypes.data`` where ``buf`` is a local / attribute binding —
  the binding outlives the call statement;
* ``buf[a:b].ctypes.data`` — the slice VIEW is a temporary, but the
  address belongs to ``buf``'s buffer, which the named base keeps
  alive (walking a Subscript/Attribute chain to a Name is accepted);
* a numpy array passed DIRECTLY as an argument (ndpointer/c_char_p
  conversion) — the argument tuple keeps it referenced for the call.

Flagged: any ``.ctypes.data`` / ``.ctypes.data_as(...)`` whose
ownership chain roots in a Call/BinOp/comprehension — i.e. a value no
name keeps alive — inside an ``hp_*`` / ``h2i_*`` call's arguments.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from . import Finding, RepoContext, register_pass

__all__ = ["NATIVE_SYMBOL_PREFIXES", "buffer_findings"]

NATIVE_SYMBOL_PREFIXES = ("hp_", "h2i_")


def _is_native_call(node: ast.Call) -> Optional[str]:
    """The native symbol name when this call targets an hp_*/h2i_*
    export (any receiver: ``lib.hp_x``, ``self._lib.h2i_y``, bare
    ``hp_x``)."""
    fn = node.func
    name = None
    if isinstance(fn, ast.Attribute):
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    if name and name.startswith(NATIVE_SYMBOL_PREFIXES):
        return name
    return None


def _chain_root(node: ast.AST) -> ast.AST:
    """Walk an Attribute/Subscript ownership chain to its root: the
    object whose lifetime owns the pointed-at buffer."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node


def _pointer_extractions(arg: ast.AST):
    """(node, base) for every ``X.ctypes.data`` / ``X.ctypes.data_as(..)``
    inside an argument expression."""
    out = []
    for node in ast.walk(arg):
        if isinstance(node, ast.Attribute) and node.attr == "data":
            inner = node.value
            if isinstance(inner, ast.Attribute) and inner.attr == "ctypes":
                out.append((node, inner.value))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "data_as"
        ):
            inner = node.func.value
            if isinstance(inner, ast.Attribute) and inner.attr == "ctypes":
                out.append((node, inner.value))
    return out


def buffer_findings(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    for path in ctx.iter_files():
        tree = ctx.tree(path)
        if tree is None:
            continue
        rel = ctx.rel(path)
        for node in ctx.nodes(path):
            if not isinstance(node, ast.Call):
                continue
            symbol = _is_native_call(node)
            if symbol is None:
                continue
            args = list(node.args) + [k.value for k in node.keywords]
            for arg in args:
                for ptr_node, base in _pointer_extractions(arg):
                    root = _chain_root(base)
                    if isinstance(root, ast.Name):
                        continue  # named binding keeps the buffer alive
                    if ctx.noqa(path, ptr_node.lineno):
                        continue
                    findings.append(Finding(
                        "buffer-safety", rel, ptr_node.lineno,
                        f"'{symbol}' is handed a pointer into a "
                        "temporary buffer (.ctypes.data on an unnamed "
                        "value): the temporary dies before the "
                        "GIL-released native call completes",
                        hint="bind the array to a local first "
                             "(buf = ...; lib.call(buf.ctypes.data, "
                             "...)) so the binding outlives the call",
                    ))
    return findings


@register_pass(
    "buffer-safety",
    "numpy buffers handed to GIL-released hp_*/h2i_* calls must be "
    "kept alive by a name, not a temporary",
)
def run(ctx: RepoContext) -> List[Finding]:
    return buffer_findings(ctx)
