"""Bench trajectory tool (ISSUE 14 satellite): the r1-rN trend,
machine-readable instead of folklore.

Every round's ``BENCH_r*.json`` is a driver capture — a single object
whose ``parsed`` field holds the headline row and whose ``tail`` text
embeds the bench's emitted JSON result lines. The absolute numbers in
those rows are per-box: CHANGES.md documents 2-6x phase swings between
rounds, which is why every row since PR 5 carries
``box_calibration_score`` (a fixed spin+memcpy workload — higher =
faster box). This tool reads all rounds, NORMALIZES each headline rate
by its row's calibration score (throughput ÷ score; latency × score, so
both become box-independent "per unit of box" figures), and emits the
trend as JSON and/or a markdown table.

Regression gate: for each metric present in the latest round AND at
least one calibrated earlier round, the latest normalized value is
compared against the best prior normalized value; a drop beyond
``--tolerance`` (default 0.5 — CI boxes are genuinely noisy even after
normalization; tighten on pinned hardware) makes the exit code nonzero
so ``make bench-trend`` can gate. Uncalibrated rows (r1-r4 headline
rows predate the score) and device/CPU-mixed comparisons are reported
but never gated: a TPU round vs a CPU-fallback round is a backend
change, not a regression.

Usage::

    python -m limitador_tpu.tools.bench_trend [--glob 'BENCH_r*.json']
        [--json out.json] [--markdown out.md] [--tolerance 0.5]
        [--gate-metrics m1,m2,...]

With no output flags the markdown table prints to stdout.
"""

from __future__ import annotations

import argparse
import glob as globlib
import json
import math
import re
import sys
from pathlib import Path
from typing import Dict, List, Optional

__all__ = [
    "load_round", "collect_rounds", "normalized_value", "trend_table",
    "regressions", "render_markdown", "main",
]

#: a metric is lower-is-better when its name or unit says latency
_LATENCY_RE = re.compile(r"(_ms$|_ms_|_p50|_p99|latency|_wait)")


def _is_latency(metric: str, unit: str) -> bool:
    return bool(_LATENCY_RE.search(metric)) or "ms" in (unit or "")


def _iter_json_lines(text: str):
    """Yield every parseable JSON object embedded line-wise in the
    driver tail (lines may be interleaved with log noise)."""
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{") or '"metric"' not in line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            yield obj


def load_round(path: Path) -> dict:
    """One driver capture -> {"round": N, "rows": {metric: row}}.
    The headline ``parsed`` row and every JSON result line found in
    ``tail`` are folded in (last occurrence of a metric wins — reruns
    within a round supersede)."""
    data = json.loads(path.read_text())
    rows: Dict[str, dict] = {}
    parsed = data.get("parsed")
    candidates: List[dict] = []
    if isinstance(parsed, dict) and "metric" in parsed:
        candidates.append(parsed)
    elif isinstance(parsed, list):
        candidates.extend(
            r for r in parsed if isinstance(r, dict) and "metric" in r
        )
    candidates.extend(_iter_json_lines(str(data.get("tail", ""))))
    for row in candidates:
        try:
            float(row.get("value"))
        except (TypeError, ValueError):
            continue
        rows[str(row["metric"])] = row
    m = re.search(r"r(\d+)", path.stem)
    return {
        "round": int(m.group(1)) if m else -1,
        "path": path.name,
        "rc": data.get("rc"),
        "rows": rows,
    }


def collect_rounds(pattern: str, root: Path) -> List[dict]:
    rounds = []
    for p in globlib.glob(str(root / pattern)):
        try:
            rounds.append(load_round(Path(p)))
        except (ValueError, OSError) as exc:
            print(f"bench_trend: skipping {p}: {exc}", file=sys.stderr)
    rounds.sort(key=lambda r: r["round"])
    return rounds


def normalized_value(row: dict) -> Optional[float]:
    """Box-normalized figure: throughput ÷ calibration score, latency
    × score. None when the row predates ``box_calibration_score``."""
    cal = row.get("box_calibration_score")
    try:
        cal = float(cal)
        value = float(row["value"])
    except (TypeError, ValueError):
        return None
    if cal <= 0 or not math.isfinite(cal):
        return None
    if _is_latency(str(row.get("metric", "")), str(row.get("unit", ""))):
        return value * cal
    return value / cal


def trend_table(rounds: List[dict]) -> dict:
    """{metric: [{round, value, normalized, calibration, device_backed,
    r2}, ...]} over every metric any round recorded."""
    out: Dict[str, List[dict]] = {}
    for rnd in rounds:
        for metric, row in rnd["rows"].items():
            fit = row.get("serving_model") or {}
            out.setdefault(metric, []).append({
                "round": rnd["round"],
                "value": float(row["value"]),
                "unit": row.get("unit", ""),
                "normalized": normalized_value(row),
                "calibration": row.get("box_calibration_score"),
                "device_backed": row.get("device_backed"),
                "model_r2": fit.get("r2"),
            })
    return out

def regressions(
    table: dict, tolerance: float, gate_metrics=None
) -> List[dict]:
    """Latest round vs best prior, normalized; a finding per metric
    whose latest normalized figure fell beyond tolerance. Only
    same-backend (device_backed equal) calibrated pairs gate."""
    found = []
    for metric, series in sorted(table.items()):
        if gate_metrics is not None and metric not in gate_metrics:
            continue
        latest = series[-1]
        if latest["normalized"] is None:
            continue
        lower_better = _is_latency(metric, latest.get("unit", ""))
        prior = [
            s for s in series[:-1]
            if s["normalized"] is not None
            and s.get("device_backed") == latest.get("device_backed")
        ]
        if not prior:
            continue
        if lower_better:
            best = min(p["normalized"] for p in prior)
            ratio = best / latest["normalized"] if latest["normalized"] else 1.0
        else:
            best = max(p["normalized"] for p in prior)
            ratio = latest["normalized"] / best if best else 1.0
        if ratio < 1.0 - tolerance:
            found.append({
                "metric": metric,
                "latest_round": latest["round"],
                "latest_normalized": latest["normalized"],
                "best_prior_normalized": best,
                "retained_share": round(ratio, 4),
                "tolerance": tolerance,
            })
    return found


def render_markdown(table: dict, regs: List[dict]) -> str:
    lines = [
        "# Bench trend (box-normalized)",
        "",
        "Normalized = value / box_calibration_score for rates, "
        "value * score for latencies; `-` = row predates the score. "
        "`dev` marks device-backed rounds.",
        "",
        "| metric | " + "trajectory (round: normalized [raw]) |",
        "|---|---|",
    ]
    for metric, series in sorted(table.items()):
        cells = []
        for s in series:
            norm = (
                f"{s['normalized']:.4g}" if s["normalized"] is not None
                else "-"
            )
            dev = " dev" if s.get("device_backed") else ""
            r2 = (
                f" R²={s['model_r2']:.2f}"
                if s.get("model_r2") is not None else ""
            )
            cells.append(
                f"r{s['round']}: {norm} [{s['value']:.4g}{dev}{r2}]"
            )
        lines.append(f"| `{metric}` | " + " → ".join(cells) + " |")
    lines.append("")
    if regs:
        lines.append("## Normalized regressions beyond tolerance")
        lines.append("")
        for r in regs:
            lines.append(
                f"- `{r['metric']}`: r{r['latest_round']} retains "
                f"{r['retained_share'] * 100:.1f}% of the best prior "
                f"normalized figure (tolerance "
                f"{r['tolerance'] * 100:.0f}%)"
            )
    else:
        lines.append(
            "No normalized regression beyond tolerance in the latest "
            "round."
        )
    lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_trend", description=__doc__.splitlines()[0]
    )
    ap.add_argument(
        "--glob", default="BENCH_r*.json",
        help="round-capture glob, relative to --root",
    )
    ap.add_argument(
        "--root", default=".", help="directory holding the captures"
    )
    ap.add_argument("--json", help="write the trend table as JSON here")
    ap.add_argument("--markdown", help="write the markdown table here")
    ap.add_argument(
        "--tolerance", type=float, default=0.5,
        help="allowed normalized drop vs best prior round (0.5 = 50%% — "
        "CI boxes stay noisy even normalized; tighten on pinned "
        "hardware)",
    )
    ap.add_argument(
        "--gate-metrics",
        help="comma-separated metrics the exit code gates on "
        "(default: every calibrated metric)",
    )
    args = ap.parse_args(argv)
    rounds = collect_rounds(args.glob, Path(args.root))
    if not rounds:
        print(f"bench_trend: no captures match {args.glob}",
              file=sys.stderr)
        return 2
    table = trend_table(rounds)
    gate = (
        {m.strip() for m in args.gate_metrics.split(",") if m.strip()}
        if args.gate_metrics else None
    )
    regs = regressions(table, args.tolerance, gate)
    payload = {
        "rounds": [
            {"round": r["round"], "path": r["path"],
             "metrics": sorted(r["rows"])}
            for r in rounds
        ],
        "trend": table,
        "regressions": regs,
        "tolerance": args.tolerance,
    }
    if args.json:
        Path(args.json).write_text(json.dumps(payload, indent=2) + "\n")
    md = render_markdown(table, regs)
    if args.markdown:
        Path(args.markdown).write_text(md)
    if not args.json and not args.markdown:
        print(md)
    else:
        for r in regs:
            print(
                f"bench_trend: REGRESSION {r['metric']} retains "
                f"{r['retained_share'] * 100:.1f}%", file=sys.stderr,
            )
    return 1 if regs else 0


if __name__ == "__main__":
    sys.exit(main())
