"""Self-driving capacity: the model-based controller closing the
admission AND membership loops (ISSUE 20, ROADMAP direction 2).

Four hand-tuned controllers steer the same p99 budget blind to each
other — the AIMD admission limit, deadline shedding, the ChunkPlanner
EWMA and lease grant sizing — and pod membership is operator-triggered
even though live ``add_host``/``drain_host`` (PR 15) and sub-second
warm joins (PR 18) made topology a cheap actuator. This package is the
single control loop over all of them:

* :mod:`actuator` — :class:`KnobSpec` + the typed :class:`Actuator`
  surface (read / apply / membership) every policy talks through, and
  :class:`ServerActuator` binding the live subsystems. The surface is
  deliberately policy-agnostic: the DRL adaptive-rate-limiting
  controller (PAPERS.md) drops in behind the same four knobs + one
  membership axis without touching any subsystem.
* :mod:`policy` — :class:`ModelPolicy`, the first (model-based) policy:
  maximize predicted throughput × p99-compliance × per-tenant fairness
  against the PR 14 fitted coefficients, with rule-based fallbacks
  while the model is in warmup.
* :mod:`controller` — :class:`CapacityController`: the cadence thread
  (inline-tickable for tests) that snapshots the PR 12 signal bus,
  asks the policy, then actuates under per-knob slew limits, the CUSUM
  drift gate, membership dwell + hysteresis, and the global "never
  actuate while a resize/join transition is active" interlock.

``--capacity-controller`` defaults to ``off`` (subsystem not
constructed — byte-identical to PR 18); ``observe`` computes and logs
every decision without actuating; ``on`` closes the loops.
"""

from .actuator import KNOBS, Actuator, KnobSpec, ServerActuator
from .controller import CTL_MODES, CapacityController
from .policy import ModelPolicy, Proposal

__all__ = [
    "CTL_MODES",
    "KNOBS",
    "METRIC_FAMILIES",
    "Actuator",
    "CapacityController",
    "KnobSpec",
    "ModelPolicy",
    "Proposal",
    "ServerActuator",
]

#: Prometheus families this subsystem writes (observability/metrics.py
#: declares them; the analysis registry pass cross-checks this tuple
#: against the declarations so the two can never drift).
METRIC_FAMILIES = (
    "ctl_mode",
    "ctl_knob",
    "ctl_actuations",
    "ctl_membership_actions",
    "ctl_interlock_holds",
    "ctl_objective",
    "ctl_pressure",
)
