"""The capacity controller: one cadence loop closing the admission
AND membership loops (ISSUE 20).

Each tick: snapshot the PR 12 signal bus, ask the policy for a
proposed operating point, then actuate — under four nested guards, in
order:

1. **Interlock** — never actuate (knobs OR membership) while a
   resize/join transition is active or proposing; the tick is counted
   and recorded, nothing moves.
2. **Drift gate** — the PR 14 CUSUM drift flag tightens every slew
   envelope to ``drift_damp`` (default ¼) and freezes membership: a
   model that just stopped predicting must not steer topology.
3. **Per-knob slew limits** — every applied value is clamped to the
   knob's envelope around its current value (``KnobSpec.slewed``), so
   no policy — model-based or DRL — can slam a knob across its range
   in one tick.
4. **Membership dwell + hysteresis** — a membership proposal must
   SUSTAIN for ``sustain_s`` (resetting whenever the proposal leaves
   its band) and the pod must have dwelt ``dwell_s`` since the last
   membership change. The policy's bands (grow below, shrink above,
   dead band between) plus these two clocks are what keep a diurnal
   ramp from flapping topology: the up-down-up unit test pins ≤ 1
   membership change.

Modes: ``observe`` computes, records and logs every decision but
applies nothing (the ``would`` field of the decision log shows what
``on`` would have done); ``on`` actuates. ``off`` never constructs
the controller at all — pinned byte-identical to PR 18.

Membership actuations and shed-floor changes emit a
``controller_actuation`` pod event — the flight recorder's
``TriggerEngine`` watches that kind, so every autoscale decision
leaves a spooled autopsy bundle. Routine knob slews do not emit (a
per-tick event would bury the timeline); they are visible in the
decision ring (``/debug/stats`` ``controller`` section), the
``ctl_*`` families and the signal tail instead.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, Optional

from .policy import ModelPolicy, Proposal

__all__ = ["CTL_MODES", "CapacityController"]

log = logging.getLogger("limitador.control")

#: --capacity-controller values; off = not constructed.
CTL_MODES = ("off", "observe", "on")


class CapacityController:
    def __init__(
        self,
        actuator,
        policy: Optional[ModelPolicy] = None,
        signals=None,            # observability.signals.SignalBus
        estimator=None,          # observability.model.ServingModelEstimator
        events=None,             # observability.events.PodEventLog
        mode: str = "observe",
        interval_s: float = 1.0,
        sustain_s: float = 5.0,
        dwell_s: float = 30.0,
        drift_damp: float = 0.25,
        history: int = 128,
        clock=time.monotonic,
    ):
        if mode not in ("observe", "on"):
            raise ValueError(
                f"controller mode {mode!r} (use off|observe|on)"
            )
        self.actuator = actuator
        self.policy = policy or ModelPolicy()
        self.mode = mode
        self.interval_s = float(interval_s)
        self.sustain_s = float(sustain_s)
        self.dwell_s = float(dwell_s)
        self.drift_damp = float(drift_damp)
        self._signals = signals
        self._estimator = estimator
        self._events = events
        self._clock = clock
        # _lock guards only the decision ring and counters (read by
        # /debug/stats and the metrics poll); actuator calls — which
        # take subsystem locks — always happen OUTSIDE it, so the
        # ``control`` lock-order domain stays outermost and leaf.
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(history), 1))
        self._ticks = 0
        self._interlock_holds = 0
        self._actuations: Dict[str, int] = {}
        self._membership_actions: Dict[str, int] = {
            "add_host": 0, "drain_host": 0,
        }
        self._last_proposal: Optional[Proposal] = None
        self._last_reason = ""
        # metric-sync baselines (poll() increments counters by delta)
        self._reported: Dict[str, float] = {}
        # membership clocks
        self._grow_sustain = 0.0
        self._shrink_sustain = 0.0
        self._last_membership_at: Optional[float] = None
        self._last_tick_at: Optional[float] = None
        # cadence thread
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- cadence -------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="capacity-controller",
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as exc:  # the loop must never die
                log.warning("capacity controller tick failed: %s", exc)

    # -- one control step ----------------------------------------------------

    def tick(self, snapshot=None) -> dict:
        """One control step (the cadence thread's body; tests call it
        inline with injected snapshots/clocks). Returns the decision
        record appended to the ring."""
        now = self._clock()
        dt = (
            now - self._last_tick_at
            if self._last_tick_at is not None else self.interval_s
        )
        self._last_tick_at = now
        snap = snapshot
        if snap is None:
            if self._signals is not None:
                snap = self._signals.snapshot()
            else:
                from ..observability.signals import ControlSignals

                snap = ControlSignals()
        current = self.actuator.read()
        specs = self.actuator.specs()
        proposal = self.policy.propose(
            snap, self._estimator, current, specs
        )
        decision: dict = {
            "ts": round(float(getattr(snap, "ts", 0.0)), 3),
            "mode": self.mode,
            "proposal": proposal.to_dict(),
            "current": {k: round(v, 4) for k, v in current.items()},
            "applied": {},
            "would": {},
            "membership": None,
            "held": None,
        }

        # 1. the global interlock: a transition in flight freezes
        # everything (its own epoch bumps are already re-steering load)
        if self.actuator.transition_active():
            decision["held"] = "interlock"
            self._finish(decision, proposal, interlock=True)
            return decision

        # 2. the drift gate: an untrusted model tightens slews and
        # freezes membership
        drifted = int(getattr(snap, "model_drift", 0)) == 1
        slew_scale = self.drift_damp if drifted else 1.0
        if drifted:
            decision["held"] = "drift_damped"

        # 3. knobs, each inside its slew envelope
        shed_floor_jump = None
        for spec in specs:
            cur = current.get(spec.name)
            want = proposal.targets.get(spec.name)
            if cur is None or want is None:
                continue
            nxt = spec.slewed(cur, want, scale=slew_scale)
            if nxt == cur:
                continue
            if self.mode == "on":
                applied = self.actuator.apply(spec.name, nxt)
                decision["applied"][spec.name] = round(applied, 4)
                if spec.name == "shed_floor" and applied != cur:
                    shed_floor_jump = (cur, applied)
                with self._lock:
                    self._actuations[spec.name] = (
                        self._actuations.get(spec.name, 0) + 1
                    )
            else:
                decision["would"][spec.name] = round(nxt, 4)
        if shed_floor_jump is not None and self._events is not None:
            # a shed-threshold jump is an SLO-protection action worth
            # an autopsy: emit the trigger-watched event kind
            self._events.emit(
                "controller_actuation", action="shed_floor",
                from_floor=shed_floor_jump[0], to_floor=shed_floor_jump[1],
                reason=proposal.reason,
            )

        # 4. membership: sustain + dwell on the policy's band proposal
        decision["membership"] = self._membership_step(
            proposal, now, dt, drifted
        )
        self._finish(decision, proposal)
        return decision

    def _membership_step(self, proposal: Proposal, now: float,
                         dt: float, drifted: bool) -> Optional[dict]:
        desire = proposal.membership
        if drifted:
            desire = 0  # the drift gate freezes topology
        if desire > 0:
            self._grow_sustain += dt
            self._shrink_sustain = 0.0
        elif desire < 0:
            self._shrink_sustain += dt
            self._grow_sustain = 0.0
        else:
            # the dead band resets both clocks — this is the
            # hysteresis that absorbs diurnal ramps
            self._grow_sustain = 0.0
            self._shrink_sustain = 0.0
            return None
        sustain = (
            self._grow_sustain if desire > 0 else self._shrink_sustain
        )
        if sustain < self.sustain_s:
            return {"desire": desire, "sustain_s": round(sustain, 3)}
        if (
            self._last_membership_at is not None
            and now - self._last_membership_at < self.dwell_s
        ):
            return {
                "desire": desire, "held": "dwell",
                "since_last_s": round(now - self._last_membership_at, 3),
            }
        feasible = (
            self.actuator.can_grow() if desire > 0
            else self.actuator.can_shrink()
        )
        if not feasible:
            return {"desire": desire, "held": "infeasible"}
        action = "add_host" if desire > 0 else "drain_host"
        if self.mode != "on":
            return {"desire": desire, "would": action}
        hosts_before = self.actuator.hosts()
        if self._events is not None:
            # emitted BEFORE the resize drives so the causal chain on
            # the timeline reads controller_actuation < join_begin/
            # resize_begin < epoch_bump < join_end/resize_end
            self._events.emit(
                "controller_actuation", action=action,
                hosts=hosts_before, reason=proposal.reason,
                pressure=round(proposal.pressure, 4),
            )
        out = (
            self.actuator.add_host() if desire > 0
            else self.actuator.drain_host()
        )
        ok = bool(out and out.get("ok"))
        self._last_membership_at = now
        self._grow_sustain = 0.0
        self._shrink_sustain = 0.0
        with self._lock:
            self._membership_actions[action] += 1
        log.warning(
            "capacity controller %s (%s): hosts %d -> %d%s",
            action, proposal.reason, hosts_before,
            self.actuator.hosts(),
            "" if ok else f" FAILED: {out}",
        )
        return {"desire": desire, "action": action, "ok": ok,
                "hosts": self.actuator.hosts()}

    def _finish(self, decision: dict, proposal: Proposal,
                interlock: bool = False) -> None:
        with self._lock:
            self._ticks += 1
            if interlock:
                self._interlock_holds += 1
            self._last_proposal = proposal
            self._last_reason = proposal.reason
            self._ring.append(decision)
        if self.mode != "on" and (
            decision["would"] or (decision["membership"] or {}).get("would")
        ):
            log.info("capacity controller (observe): %s", decision)

    # -- surfaces ------------------------------------------------------------

    def signal_fields(self) -> dict:
        """The controller tail of ``ControlSignals`` (ISSUE 20):
        active knob values + the last actuation reason, appended at
        the END of FIELDS so the observation vector only grows."""
        cur = self.actuator.read()
        with self._lock:
            reason = self._last_reason
        return {
            "ctl_admission_ceiling": float(
                cur.get("admission_ceiling", 0.0)
            ),
            "ctl_shed_floor": float(cur.get("shed_floor", 0.0)),
            "ctl_chunk_target_ms": float(
                cur.get("chunk_target_ms", 0.0)
            ),
            "ctl_lease_scale": float(cur.get("lease_scale", 0.0)),
            "ctl_last_reason": reason,
        }

    def controller_debug(self) -> dict:
        """The ``controller`` section of ``/debug/stats``."""
        with self._lock:
            ring = list(self._ring)
            last = (
                self._last_proposal.to_dict()
                if self._last_proposal is not None else None
            )
            out = {
                "mode": self.mode,
                "interval_s": self.interval_s,
                "sustain_s": self.sustain_s,
                "dwell_s": self.dwell_s,
                "ticks": self._ticks,
                "interlock_holds": self._interlock_holds,
                "actuations": dict(self._actuations),
                "membership_actions": dict(self._membership_actions),
                "grow_sustain_s": round(self._grow_sustain, 3),
                "shrink_sustain_s": round(self._shrink_sustain, 3),
            }
        out["knobs"] = {
            k: round(v, 4) for k, v in self.actuator.read().items()
        }
        out["specs"] = [s.to_dict() for s in self.actuator.specs()]
        out["hosts"] = self.actuator.hosts()
        out["last_proposal"] = last
        out["decisions"] = ring[-16:]
        return out

    def stats(self) -> dict:
        """Flat counters (library_stats-style; the drill asserts on
        these)."""
        with self._lock:
            return {
                "ctl_ticks": self._ticks,
                "ctl_interlock_holds": self._interlock_holds,
                "ctl_knob_actuations": sum(self._actuations.values()),
                "ctl_hosts_added":
                    self._membership_actions["add_host"],
                "ctl_hosts_drained":
                    self._membership_actions["drain_host"],
            }

    def poll(self, metrics) -> None:
        """Render-time hook (``PrometheusMetrics.attach_render_hook``):
        refresh the ``ctl_*`` families. Counters sync by delta against
        the internal counts so a render never double-increments."""
        metrics.ctl_mode.set(CTL_MODES.index(self.mode))
        for name, value in self.actuator.read().items():
            metrics.ctl_knob.labels(name).set(value)
        with self._lock:
            holds = self._interlock_holds
            actuations = dict(self._actuations)
            membership = dict(self._membership_actions)
            last = self._last_proposal
            reported = self._reported
        d = holds - reported.get("interlock", 0)
        if d > 0:
            metrics.ctl_interlock_holds.inc(d)
            reported["interlock"] = holds
        for name, count in actuations.items():
            d = count - reported.get(f"knob:{name}", 0)
            if d > 0:
                metrics.ctl_actuations.labels(name).inc(d)
                reported[f"knob:{name}"] = count
        for action, count in membership.items():
            d = count - reported.get(f"member:{action}", 0)
            if d > 0:
                metrics.ctl_membership_actions.labels(action).inc(d)
                reported[f"member:{action}"] = count
        if last is not None:
            metrics.ctl_objective.set(last.objective)
            metrics.ctl_pressure.set(last.pressure)
