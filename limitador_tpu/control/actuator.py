"""The typed actuation surface between a capacity policy and the
serving subsystems (ISSUE 20).

A policy never touches a subsystem directly: it reads knob values and
writes knob targets through :class:`Actuator`, and the controller is
the only caller of :meth:`Actuator.apply`. That indirection is the
whole point — :class:`KnobSpec` carries the bounds, slew limit and
neutral value per knob, so ANY policy (the model-based first policy
here, a DRL policy later) is automatically clamped to the same safe
envelope, and a test can substitute a recording actuator without
constructing any subsystem.

The four knobs plus one membership axis:

==================  ======================================  =========
knob                subsystem surface                        neutral
==================  ======================================  =========
admission_ceiling   ``AdaptiveLimiter.set_ceiling``          hard max
shed_floor          ``AdmissionController.shed_floor``       0
chunk_target_ms     ``ChunkPlanner.retarget`` (all lanes)    2.0
lease_scale         ``LeaseBroker.grant_scale``              1.0
membership          ``PodResizeCoordinator`` add/drain/join  hold
==================  ======================================  =========

Membership grows prefer the PR 18 warm-standby ``join_host`` path
(sub-second promotion) when a standby address is available, falling
back to the PR 15 cold ``add_host``; shrinks always use
``drain_host`` (the tail host drains its slices to the survivors).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["KNOBS", "Actuator", "KnobSpec", "ServerActuator"]


class KnobSpec:
    """One knob's safe envelope: bounds, per-tick slew and neutral.

    ``slew`` is the max relative change per controller tick for
    multiplicative knobs (``additive=False``): the value may move at
    most ``slew * max(|current|, lo)`` per tick. Additive knobs (the
    shed floor — an integer priority level) move at most ``slew``
    absolute per tick."""

    __slots__ = ("name", "lo", "hi", "slew", "neutral", "integer",
                 "additive")

    def __init__(self, name: str, lo: float, hi: float, slew: float,
                 neutral: float, integer: bool = False,
                 additive: bool = False):
        self.name = name
        self.lo = float(lo)
        self.hi = float(hi)
        self.slew = float(slew)
        self.neutral = float(neutral)
        self.integer = bool(integer)
        self.additive = bool(additive)

    def clamp(self, value: float) -> float:
        v = min(max(float(value), self.lo), self.hi)
        return float(int(round(v))) if self.integer else v

    def max_step(self, current: float) -> float:
        """The largest move allowed from ``current`` in one tick."""
        if self.additive:
            return self.slew
        return self.slew * max(abs(float(current)), self.lo, 1e-9)

    def slewed(self, current: float, target: float,
               scale: float = 1.0) -> float:
        """``target`` clamped to the slew envelope around ``current``
        (``scale`` < 1 — the drift gate — tightens the envelope)."""
        step = self.max_step(current) * max(float(scale), 0.0)
        lo, hi = float(current) - step, float(current) + step
        return self.clamp(min(max(float(target), lo), hi))

    def to_dict(self) -> dict:
        return {
            "name": self.name, "lo": self.lo, "hi": self.hi,
            "slew": self.slew, "neutral": self.neutral,
            "integer": self.integer, "additive": self.additive,
        }


#: The default knob envelopes. ``admission_ceiling`` bounds are
#: refined per-server by :class:`ServerActuator` (hi = the configured
#: --max-inflight hard cap, which the controller may only tighten).
KNOBS = (
    KnobSpec("admission_ceiling", lo=64, hi=4096, slew=0.25,
             neutral=4096, integer=True),
    KnobSpec("shed_floor", lo=0, hi=3, slew=1.0, neutral=0,
             integer=True, additive=True),
    KnobSpec("chunk_target_ms", lo=0.5, hi=8.0, slew=0.25, neutral=2.0),
    KnobSpec("lease_scale", lo=0.25, hi=4.0, slew=0.25, neutral=1.0),
)


class Actuator:
    """The surface a capacity policy actuates through. Implementations
    expose only the knobs whose subsystems exist (``specs()`` is the
    contract); membership methods are no-ops returning ``None`` when
    no resize coordinator is bound."""

    def specs(self) -> Tuple[KnobSpec, ...]:
        raise NotImplementedError

    def read(self) -> Dict[str, float]:
        """Live value of every knob in ``specs()``."""
        raise NotImplementedError

    def apply(self, name: str, value: float) -> float:
        """Write one knob (already slew-limited by the controller);
        returns the value actually applied after subsystem clamps."""
        raise NotImplementedError

    # -- membership axis -----------------------------------------------------

    def hosts(self) -> int:
        return 0

    def transition_active(self) -> bool:
        """True while a resize/join transition is in flight — the
        controller's global actuation interlock."""
        return False

    def can_grow(self) -> bool:
        return False

    def can_shrink(self) -> bool:
        return False

    def add_host(self) -> Optional[dict]:
        return None

    def drain_host(self) -> Optional[dict]:
        return None


class ServerActuator(Actuator):
    """Binds the live subsystems. Every constructor argument is
    optional: a missing subsystem simply drops its knob from
    ``specs()`` (a host-only server still gets admission knobs; a
    server without a pod gets no membership axis)."""

    def __init__(
        self,
        overload=None,           # admission.overload.AdaptiveLimiter
        admission=None,          # admission.AdmissionController
        planners=(),             # tpu.batcher.ChunkPlanner instances
        broker=None,             # lease.broker.LeaseBroker
        coordinator=None,        # server.resize.PodResizeCoordinator
        standby_addresses=(),    # warm-standby lane addresses (PR 18)
        min_hosts: int = 1,
        max_hosts: int = 8,
    ):
        self._overload = overload
        self._admission = admission
        self._planners = [p for p in planners if p is not None]
        self._broker = broker
        self._coordinator = coordinator
        self._standbys: List[str] = [str(a) for a in standby_addresses
                                     if a]
        self.min_hosts = max(int(min_hosts), 1)
        self.max_hosts = max(int(max_hosts), self.min_hosts)
        self._lock = threading.Lock()  # guards the standby pool
        specs = []
        if overload is not None:
            hard = float(getattr(overload, "hard_max", overload.max_inflight))
            specs.append(KnobSpec(
                "admission_ceiling",
                lo=min(64.0, hard), hi=hard, slew=0.25, neutral=hard,
                integer=True,
            ))
        if admission is not None:
            specs.append(KnobSpec(
                "shed_floor", lo=0, hi=3, slew=1.0, neutral=0,
                integer=True, additive=True,
            ))
        if self._planners:
            specs.append(KnobSpec(
                "chunk_target_ms", lo=0.5, hi=8.0, slew=0.25,
                neutral=self._planners[0].target_s * 1e3,
            ))
        if broker is not None:
            specs.append(KnobSpec(
                "lease_scale", lo=0.25, hi=4.0, slew=0.25, neutral=1.0,
            ))
        self._specs = tuple(specs)

    def specs(self) -> Tuple[KnobSpec, ...]:
        return self._specs

    def read(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        if self._overload is not None:
            out["admission_ceiling"] = float(self._overload.max_inflight)
        if self._admission is not None:
            out["shed_floor"] = float(self._admission.shed_floor)
        if self._planners:
            out["chunk_target_ms"] = float(
                self._planners[0].target_s * 1e3
            )
        if self._broker is not None:
            out["lease_scale"] = float(self._broker.grant_scale)
        return out

    def apply(self, name: str, value: float) -> float:
        if name == "admission_ceiling" and self._overload is not None:
            return float(self._overload.set_ceiling(int(value)))
        if name == "shed_floor" and self._admission is not None:
            floor = max(0, min(int(value), 3))
            self._admission.shed_floor = floor
            return float(floor)
        if name == "chunk_target_ms" and self._planners:
            applied = 0.0
            for planner in self._planners:
                applied = planner.retarget(float(value) / 1e3) * 1e3
            return applied
        if name == "lease_scale" and self._broker is not None:
            scale = min(max(float(value), 0.25), 4.0)
            self._broker.grant_scale = scale
            return scale
        return float(value)  # unknown knob: inert (policy bug, not a crash)

    # -- membership axis -----------------------------------------------------

    def hosts(self) -> int:
        coord = self._coordinator
        if coord is None:
            return 0
        return int(coord.router.topology.hosts)

    def transition_active(self) -> bool:
        coord = self._coordinator
        return bool(coord is not None and coord.busy)

    def can_grow(self) -> bool:
        with self._lock:
            has_standby = bool(self._standbys)
        return (
            self._coordinator is not None
            and has_standby
            and self.hosts() < self.max_hosts
        )

    def can_shrink(self) -> bool:
        return (
            self._coordinator is not None
            and self.hosts() > self.min_hosts
        )

    def add_host(self) -> Optional[dict]:
        """Grow by one: promote the next warm standby over the PR 18
        join path. The address is only consumed on success — a failed
        join returns it to the pool so the next tick can retry."""
        coord = self._coordinator
        with self._lock:
            if coord is None or not self._standbys:
                return None
            address = self._standbys.pop(0)
        try:
            out = coord.join_host(address)
        except Exception as exc:
            with self._lock:
                self._standbys.insert(0, address)
            return {"ok": False, "error": str(exc), "address": address}
        if not out.get("ok"):
            with self._lock:
                self._standbys.insert(0, address)
        return out

    def drain_host(self) -> Optional[dict]:
        """Shrink by one: the tail host drains its slices to the
        survivors (PR 15). Its address returns to the standby pool —
        the drained process keeps serving its lane, so a later grow
        can re-join it warm."""
        coord = self._coordinator
        if coord is None:
            return None
        hosts = self.hosts()
        address = coord._peers.get(hosts - 1)
        try:
            out = coord.drain_host()
        except Exception as exc:
            return {"ok": False, "error": str(exc)}
        if out.get("ok") and address:
            with self._lock:
                self._standbys.append(address)
        return out

    def standby_pool(self) -> List[str]:
        with self._lock:
            return list(self._standbys)
