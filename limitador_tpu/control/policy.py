"""The first capacity policy: model-based joint optimization
(ISSUE 20).

``ModelPolicy`` proposes one operating point per tick — a desired
value per knob plus a membership direction — chosen to maximize

    J = predicted_throughput × p99_compliance × fairness

against the PR 14 serving model: ``predicted_throughput`` and the p99
forecast come from ``ServingModelEstimator.what_if`` (the fitted
latency/throughput coefficients), ``p99_compliance`` is
``min(1, budget / predicted_p99)``, and ``fairness`` discounts the
objective by the priority-weighted shed rate (shedding critical
traffic costs 8× what shedding low does — the per-tenant fairness
axis of the Multi-Objective Adaptive Rate Limiting formulation,
reduced to the priority classes the admission plane already has).

While the model is in warmup (R² = 0, headroom unknown) every term
falls back to a rule driven by the raw signals — queue-wait ratio and
SLO burn — so a cold server is steered conservatively rather than not
at all. The policy only PROPOSES: the controller owns slew limits,
the drift gate, membership dwell/hysteresis and the interlock.

The surface is deliberately minimal — ``propose(snapshot, estimator,
current, specs) -> Proposal`` — so the DRL policy (PAPERS.md) is a
drop-in: same observation (the pinned ``ControlSignals.vector()``),
same action space (knob targets + membership direction).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

__all__ = ["ModelPolicy", "Proposal"]

#: fairness weights per priority class (low..critical): the objective
#: discount for one shed/second of each class.
_FAIRNESS_WEIGHTS = (1.0, 2.0, 4.0, 8.0)

_PRIORITY_ORDER = ("low", "normal", "high", "critical")


class Proposal:
    """One tick's proposed operating point.

    ``targets`` — desired value per knob (pre-slew; the controller
    clamps). ``membership`` — desired direction: +1 grow, -1 shrink,
    0 hold. ``reason`` — the dominant driver, for the decision log and
    the ``ctl_last_reason`` signal field. ``objective`` — J evaluated
    at the proposed point (0.0 while the model is in warmup).
    ``pressure`` — the scalar overload signal the membership bands
    compare against."""

    __slots__ = ("targets", "membership", "reason", "objective",
                 "pressure", "terms")

    def __init__(self, targets: Dict[str, float], membership: int = 0,
                 reason: str = "steady", objective: float = 0.0,
                 pressure: float = 0.0,
                 terms: Optional[dict] = None):
        self.targets = dict(targets)
        self.membership = int(membership)
        self.reason = reason
        self.objective = float(objective)
        self.pressure = float(pressure)
        self.terms = dict(terms or {})

    def to_dict(self) -> dict:
        return {
            "targets": {k: round(v, 4) for k, v in self.targets.items()},
            "membership": self.membership,
            "reason": self.reason,
            "objective": round(self.objective, 4),
            "pressure": round(self.pressure, 4),
            "terms": self.terms,
        }


class ModelPolicy:
    def __init__(
        self,
        budget_ms: float = 2.0,
        grow_headroom: float = 1.2,
        shrink_headroom: float = 3.0,
        idle_pressure: float = 0.05,
        ceiling_margin: float = 1.5,
    ):
        #: the p99 budget compliance is judged against (the estimator's
        #: own budget when one is attached overrides this default)
        self.budget_ms = float(budget_ms)
        #: membership hysteresis bands on capacity headroom: sustained
        #: headroom BELOW grow_headroom proposes +1, sustained headroom
        #: ABOVE shrink_headroom proposes -1; the dead band between
        #: them absorbs diurnal ramps.
        self.grow_headroom = float(grow_headroom)
        self.shrink_headroom = float(shrink_headroom)
        #: warmup fallback: pressure below this proposes shrink,
        #: pressure >= 1.0 proposes grow.
        self.idle_pressure = float(idle_pressure)
        #: admission ceiling target = sustainable concurrency ×
        #: this margin (Little's law headroom for burst absorption)
        self.ceiling_margin = float(ceiling_margin)

    # -- signal digestion ----------------------------------------------------

    def _budget(self, estimator) -> float:
        if estimator is not None:
            try:
                return float(estimator.budget_ms)
            except Exception:
                pass
        return self.budget_ms

    def _pressure(self, snap, budget_ms: float) -> Tuple[float, dict]:
        """One scalar overload signal in [0, inf): 1.0 = at capacity.
        The max of SLO burn, queue-wait/budget, and inverse model
        headroom — whichever subsystem sees saturation first wins."""
        burn = max(float(snap.slo_burn_5m), 0.0)
        queue_ratio = (
            float(snap.queue_wait_ms) / budget_ms if budget_ms > 0
            else 0.0
        )
        headroom = float(snap.capacity_headroom_ratio)
        inv_headroom = 1.0 / headroom if headroom > 0 else 0.0
        terms = {
            "burn": round(burn, 4),
            "queue_ratio": round(queue_ratio, 4),
            "headroom": round(headroom, 4),
        }
        return max(burn, queue_ratio, inv_headroom), terms

    def _fairness(self, snap) -> float:
        """1 / (1 + priority-weighted shed rate): shedding at all
        discounts the objective, shedding high classes discounts it
        hardest."""
        weighted = 0.0
        for i, pname in enumerate(_PRIORITY_ORDER):
            weighted += _FAIRNESS_WEIGHTS[i] * float(
                snap.shed_rate_by_priority.get(pname, 0.0)
            )
        return 1.0 / (1.0 + weighted)

    def _model_view(self, snap, estimator) -> Optional[dict]:
        """The fitted forecast at the current operating point, or None
        while the model can't be trusted (absent / warmup / R² = 0)."""
        if estimator is None or float(snap.model_r2) <= 0.0:
            return None
        try:
            view = estimator.what_if()
        except Exception:
            return None
        if not view or not view.get("max_decisions_per_sec"):
            return None
        return view

    def objective(self, snap, rate: float, p99_ms: float,
                  budget_ms: float) -> float:
        """J = rate × min(1, budget/p99) × fairness."""
        compliance = (
            min(1.0, budget_ms / p99_ms) if p99_ms > 0 else 1.0
        )
        return float(rate) * compliance * self._fairness(snap)

    # -- the proposal --------------------------------------------------------

    def propose(self, snap, estimator, current: Dict[str, float],
                specs) -> Proposal:
        budget_ms = self._budget(estimator)
        pressure, terms = self._pressure(snap, budget_ms)
        view = self._model_view(snap, estimator)
        by_name = {spec.name: spec for spec in specs}
        targets: Dict[str, float] = {}

        if "admission_ceiling" in by_name:
            targets["admission_ceiling"] = self._ceiling_target(
                snap, view, by_name["admission_ceiling"],
                current.get("admission_ceiling", 0.0),
                pressure, budget_ms,
            )
        if "shed_floor" in by_name:
            targets["shed_floor"] = self._shed_floor_target(
                snap, current.get("shed_floor", 0.0)
            )
        if "chunk_target_ms" in by_name:
            targets["chunk_target_ms"] = self._chunk_target(
                snap, by_name["chunk_target_ms"], pressure, budget_ms
            )
        if "lease_scale" in by_name:
            targets["lease_scale"] = self._lease_target(
                snap, by_name["lease_scale"], pressure
            )

        membership, reason = self._membership(snap, pressure, terms)
        objective = 0.0
        if view is not None:
            objective = self.objective(
                snap,
                float(view.get("predicted_decisions_per_sec", 0.0)),
                float(view.get("predicted_latency_ms", 0.0)),
                budget_ms,
            )
        return Proposal(
            targets, membership=membership, reason=reason,
            objective=objective, pressure=pressure, terms=terms,
        )

    # -- per-knob desired values ---------------------------------------------

    def _ceiling_target(self, snap, view, spec, current, pressure,
                        budget_ms) -> float:
        if view is not None:
            # Little's law: sustainable in-flight = rate × latency
            # budget; the margin leaves burst headroom. The fitted
            # max rate already reflects the box (calibration-normed).
            max_rate = float(view.get("max_decisions_per_sec", 0.0))
            little = max_rate * (budget_ms / 1e3) * self.ceiling_margin
            target = little if little > 0 else spec.neutral
            if float(snap.slo_burn_5m) >= 1.0:
                # burning the SLO overrides the forecast: tighten
                target = min(target, current * 0.75)
            return spec.clamp(target)
        # warmup rules: queue eating the budget -> tighten; calm and
        # no burn -> relax toward the hard max.
        if pressure >= 1.0:
            return spec.clamp(current * 0.75)
        if pressure <= 0.5:
            return spec.clamp(current * 1.25)
        return spec.clamp(current)

    def _shed_floor_target(self, snap, current) -> float:
        burn = float(snap.slo_burn_5m)
        if burn >= 1.0 or int(snap.slo_breached):
            return min(current + 1.0, 3.0)  # shed the next class up
        if burn <= 0.25:
            return max(current - 1.0, 0.0)  # recover toward shed-nothing
        return current

    def _chunk_target(self, snap, spec, pressure, budget_ms) -> float:
        # Queueing has eaten the budget: tighten the device slice so
        # decisions start flowing (the ChunkPlanner halves internally
        # too — this moves the baseline the halving applies to). Calm:
        # a full-budget slice minimizes launch count.
        if pressure >= 1.0:
            return spec.clamp(budget_ms / 2.0)
        if pressure <= 0.5:
            return spec.clamp(budget_ms)
        return spec.clamp(spec.neutral)

    def _lease_target(self, snap, spec, pressure) -> float:
        if int(snap.near_exhaustion) > 0:
            # tenants near their limit: leased headroom trades
            # exactness exactly where it hurts — shrink grants
            return spec.clamp(0.5)
        if pressure >= 1.0:
            # saturated: bigger grants amortize more device work into
            # the native lease lane
            return spec.clamp(2.0)
        return spec.clamp(spec.neutral)

    # -- membership direction ------------------------------------------------

    def _membership(self, snap, pressure, terms) -> Tuple[int, str]:
        headroom = float(snap.capacity_headroom_ratio)
        if headroom > 0:
            # model-known bands (the controller adds dwell + sustain)
            if headroom < self.grow_headroom:
                return 1, "headroom_burn"
            if headroom > self.shrink_headroom:
                return -1, "headroom_idle"
        else:
            # warmup fallback: the raw pressure signal
            if pressure >= 1.0:
                return 1, "pressure_burn"
            if pressure <= self.idle_pressure:
                return -1, "pressure_idle"
        if pressure >= 1.0:
            return 0, "slo_burn" if terms["burn"] >= 1.0 else "queue_wait"
        return 0, "steady"
