from .mesh import (
    ShardedBatchResult,
    ShardedCounterState,
    make_mesh,
    make_sharded_table,
    sharded_check_and_update,
)

__all__ = [
    "ShardedBatchResult",
    "ShardedCounterState",
    "make_mesh",
    "make_sharded_table",
    "sharded_check_and_update",
]
