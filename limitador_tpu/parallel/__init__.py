from .mesh import (
    ShardedBatchResult,
    ShardedCounterState,
    batch_sharding,
    make_mesh,
    make_sharded_table,
    sharded_check_and_update,
    sharded_clear_cells,
)

__all__ = [
    "ShardedBatchResult",
    "ShardedCounterState",
    "batch_sharding",
    "make_mesh",
    "make_sharded_table",
    "sharded_check_and_update",
    "sharded_clear_cells",
]
