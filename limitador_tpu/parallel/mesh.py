"""Multi-chip sharded counter table.

TPU-native analogue of the reference's counter-distribution topologies
(SURVEY.md §2.3, /root/reference/doc/topologies.md):

- **Owner-sharded keys (exact)**: the counter table is sharded by slot over
  the mesh ("shard" axis); the host routes each hit to its owner device
  (the ICI equivalent of Redis-cluster hash-tag sharding, keys.rs:1-13).
  Requests may span devices: admission is all-or-nothing per request, so
  each fixpoint sweep combines per-device hit verdicts with a cross-device
  ``pmin`` over the replicated request vector. Exactness is preserved —
  the fixpoint argument of ops/kernel.py is unchanged, the AND just rides
  ICI.
- **Replicated global counters (psum)**: counters of "global limit"
  namespaces hold a per-device partial count; their effective value is
  ``psum`` of partials (the CRDT read-as-sum of
  distributed/cr_counter_value.rs:38-46 mapped onto ICI collectives).
  Admission uses the psum'd base plus the device-local prefix, so
  over-admission is bounded by one batch per remote device — the same
  bounded-inaccuracy contract the reference documents for its distributed
  and cached-Redis modes (redis_cached.rs:25-41).

Layout: values/expiry are [n_shards, local_capacity+1] with
PartitionSpec("shard", None); hit arrays are [n_shards, H_local] sharded the
same way; request vectors are replicated.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.kernel import check_and_update_core, update_core

__all__ = [
    "ShardedCounterState",
    "ShardedBatchResult",
    "make_sharded_table",
    "make_mesh",
    "sharded_check_and_update",
    "sharded_update",
]

_NEVER = jnp.iinfo(jnp.int32).max


def _shard_map(fn, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` across JAX versions: the public API (>= 0.6,
    ``check_vma``) vs ``jax.experimental.shard_map`` (0.4.x,
    ``check_rep``). Replication checking is disabled either way — the
    cross-device pmin/psum coupling below is deliberate."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


class ShardedCounterState(NamedTuple):
    values: jax.Array     # int32[n_shards, L+1] sharded over "shard"
    expiry_ms: jax.Array  # int32[n_shards, L+1] sharded over "shard"


class ShardedBatchResult(NamedTuple):
    admitted: jax.Array   # bool[R] replicated
    hit_ok: jax.Array     # bool[n_shards, H_local]
    remaining: jax.Array  # int32[n_shards, H_local]
    ttl_ms: jax.Array     # int32[n_shards, H_local]


def make_mesh(devices=None, axis: str = "shard") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(devices, (axis,))


def make_sharded_table(
    mesh: Mesh, local_capacity: int, axis: str = "shard"
) -> ShardedCounterState:
    n = mesh.shape[axis]
    sharding = NamedSharding(mesh, P(axis, None))
    make = lambda: jax.device_put(
        jnp.zeros((n, local_capacity + 1), jnp.int32), sharding
    )
    return ShardedCounterState(values=make(), expiry_ms=make())


def _local_step(values, expiry, slots, deltas, maxes, windows, req_ids,
                fresh, bucket, is_global, now_ms, num_req, axis,
                global_region):
    """Per-device admission over the local shard; runs inside shard_map.

    Delegates to ops/kernel.py's shared ``check_and_update_core`` with two
    cross-device hooks:

    - ``vote_combine``: requests may span devices; admission is all-or-
      nothing, so per-device verdicts AND across the mesh via ``pmin``
      (devices without hits for a request vote True).
    - ``base_hook``: global counters occupy the same slot (< global_region)
      on every shard, each holding a per-device partial; the effective base
      is the psum of live partials over that compact region (the CRDT
      read-as-sum riding ICI). In-batch remote contributions are not
      visible until the next batch — bounded over-admission, as in the
      reference's distributed mode.
    """
    live_partial = jnp.where(now_ms < expiry[:global_region],
                             values[:global_region], 0)
    global_vals = lax.psum(live_partial, axis)
    s_glob = is_global[jnp.argsort(slots, stable=True)]

    def base_hook(v_local, s_slot):
        safe_idx = jnp.minimum(s_slot, global_region - 1)
        return jnp.where(s_glob, global_vals[safe_idx], v_local)

    def vote_combine(local_vote):
        return lax.pmin(local_vote.astype(jnp.int32), axis).astype(bool)

    return check_and_update_core(
        values, expiry, slots, deltas, maxes, windows, req_ids, fresh,
        bucket, now_ms, num_req, vote_combine=vote_combine,
        base_hook=base_hook,
    )


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "global_region"),
    donate_argnums=(1,),
)
def sharded_check_and_update(
    mesh: Mesh,
    state: ShardedCounterState,
    slots: jax.Array,       # int32[n, H_local] owner-local slot per hit
    deltas: jax.Array,      # int32[n, H_local]
    maxes: jax.Array,       # int32[n, H_local]
    windows_ms: jax.Array,  # int32[n, H_local]
    req_ids: jax.Array,     # int32[n, H_local] global request ids
    fresh: jax.Array,       # bool[n, H_local]
    bucket: jax.Array,      # bool[n, H_local] GCRA token-bucket hits
    is_global: jax.Array,   # bool[n, H_local] psum-replicated counter hits
    now_ms: jax.Array,      # int32 scalar
    axis: str = "shard",
    global_region: int = 1024,
) -> Tuple[ShardedCounterState, ShardedBatchResult]:
    """One fused multi-chip check-and-update step over the sharded table.

    Bucket hits are owner-sharded only (the host routes them like any
    exact counter; a TAT cell cannot be a psum global partial, so bucket
    counters in global namespaces stay on the host's exact path)."""
    num_req = slots.shape[0] * slots.shape[1]

    def fn(values, expiry, slots, deltas, maxes, windows, req_ids, fresh,
           bucket, is_global):
        (nv, ne, admitted, ok, remaining, ttl) = _local_step(
            values[0], expiry[0], slots[0], deltas[0], maxes[0], windows[0],
            req_ids[0], fresh[0], bucket[0], is_global[0], now_ms, num_req,
            axis, global_region,
        )
        return (
            nv[None], ne[None], admitted, ok[None], remaining[None], ttl[None]
        )

    spec = P(axis, None)
    rep = P()
    nv, ne, admitted, ok, remaining, ttl = _shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec,) * 10,
        out_specs=(spec, spec, rep, spec, spec, spec),
    )(state.values, state.expiry_ms, slots, deltas, maxes, windows_ms,
      req_ids, fresh, bucket, is_global)
    return (
        ShardedCounterState(nv, ne),
        ShardedBatchResult(admitted, ok, remaining, ttl),
    )


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis"), donate_argnums=(1,),
)
def sharded_update(
    mesh: Mesh,
    state: ShardedCounterState,
    slots: jax.Array,       # int32[n, H_local]
    deltas: jax.Array,      # int32[n, H_local]
    windows_ms: jax.Array,  # int32[n, H_local]
    fresh: jax.Array,       # bool[n, H_local]
    bucket: jax.Array,      # bool[n, H_local]
    now_ms: jax.Array,      # int32 scalar
    axis: str = "shard",
) -> ShardedCounterState:
    """Unconditional batched increments over the sharded table (the
    Report/update and write-behind-authority path): per-shard saturating
    scatter-adds, no admission, no cross-device coupling — a global
    counter's delta simply lands in one shard's partial."""

    def fn(values, expiry, slots, deltas, windows, fresh, bucket):
        nv, ne = update_core(
            values[0], expiry[0], slots[0], deltas[0], windows[0], fresh[0],
            bucket[0], now_ms,
        )
        return nv[None], ne[None]

    spec = P(axis, None)
    nv, ne = _shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec,) * 7,
        out_specs=(spec, spec),
    )(state.values, state.expiry_ms, slots, deltas, windows_ms, fresh,
      bucket)
    return ShardedCounterState(nv, ne)
