"""Multi-chip sharded counter table.

TPU-native analogue of the reference's counter-distribution topologies
(SURVEY.md §2.3, /root/reference/doc/topologies.md):

- **Owner-sharded keys (exact)**: the counter table is sharded by slot over
  the mesh ("shard" axis); the host routes each hit to its owner device
  (the ICI equivalent of Redis-cluster hash-tag sharding, keys.rs:1-13).
  Requests may span devices: admission is all-or-nothing per request, so
  each fixpoint sweep combines per-device hit verdicts with a cross-device
  ``pmin`` over the replicated request vector. Exactness is preserved —
  the fixpoint argument of ops/kernel.py is unchanged, the AND just rides
  ICI.
- **Replicated global counters (psum)**: counters of "global limit"
  namespaces hold a per-device partial count; their effective value is
  ``psum`` of partials (the CRDT read-as-sum of
  distributed/cr_counter_value.rs:38-46 mapped onto ICI collectives).
  Admission uses the psum'd base plus the device-local prefix, so
  over-admission is bounded by one batch per remote device — the same
  bounded-inaccuracy contract the reference documents for its distributed
  and cached-Redis modes (redis_cached.rs:25-41).

Layout: values/expiry are [n_shards, local_capacity+1] with
PartitionSpec("shard", None); hit arrays are [n_shards, H_local] sharded the
same way; request vectors are replicated.

Collective-lean variants
------------------------
Collectives only pay for themselves when a batch actually needs them, and
BENCH_r05 showed the always-coupled launch scaling NEGATIVELY (1.91M/s on
8 shards vs 2.60M/s on one): every batch paid a psum over the global
region plus a pmin over the full replicated request vector, whether or
not any hit was global or any request spanned shards. The host stages
per-shard hits and KNOWS both facts, so ``sharded_check_and_update``
takes two static flags:

- ``coupled=False`` — no request spans shards: request ids are
  SHARD-LOCAL (``req_ids`` in [0, H_local), ``num_req = H_local``), the
  cross-device ``pmin`` disappears, and ``admitted`` comes back
  ``[n_shards, H_local]`` sharded like the hit arrays (the caller indexes
  it by the request's owner shard). The per-sweep ``segment_min`` also
  shrinks n_shards-fold.
- ``has_global=False`` — no psum-region hit in the batch: the global
  partial sum (and its all-reduce) is skipped entirely.

The default (``coupled=True, has_global=True``) is the fully coupled
program; the four (coupled, has_global) combinations are four compiled
programs, selected per batch by the storage's staging pass. Batch inputs
should be ``jax.device_put`` with :func:`batch_sharding` so each shard
receives only its own rows — handing the jit replicated host arrays makes
XLA materialize every shard's hits on every device and slice them back
out, which is exactly the replication this path exists to avoid (the
HLO regression test in tests/test_sharded.py pins this).
"""

from __future__ import annotations

import base64
import functools
import threading
import time
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.kernel import check_and_update_core, update_core

__all__ = [
    "ShardedCounterState",
    "ShardedBatchResult",
    "make_sharded_table",
    "make_mesh",
    "make_global_mesh",
    "batch_sharding",
    "sharded_check_and_update",
    "sharded_update",
    "sharded_clear_cells",
    "sharded_drain_top_hits",
    "PodInfo",
    "initialize_pod",
    "pod_info",
    "host_local_to_global",
    "pod_sync",
    "pod_barrier",
    "PodPsumLane",
    "PodMembership",
    "PeerPsumTransport",
    "make_host_mesh",
    "METRIC_FAMILIES",
]

#: metric families this subsystem owns (cross-checked against
#: observability/metrics.py by the analysis registry pass): the
#: lockstep pod psum lane (ISSUE 13) — global-namespace limits decided
#: locally on every host against read-as-sum partials, instead of
#: funneling through one pin host.
METRIC_FAMILIES = (
    "pod_psum_namespaces",
    "pod_psum_decisions",
    "pod_psum_limited",
    "pod_psum_exchanges",
    "pod_psum_cells",
    "pod_psum_remote_slots",
)

_NEVER = jnp.iinfo(jnp.int32).max


def _shard_map(fn, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` across JAX versions: the public API (>= 0.6,
    ``check_vma``) vs ``jax.experimental.shard_map`` (0.4.x,
    ``check_rep``). Replication checking is disabled either way — the
    cross-device pmin/psum coupling below is deliberate."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


class ShardedCounterState(NamedTuple):
    """``hits`` is the per-slot traffic accumulator (shard-local counts;
    a global counter's traffic lands in each hitting shard's row —
    drains sum it host-side). ``make_sharded_table`` always creates it;
    the sharded kernels below require it present (None is tolerated
    only as a passthrough on rebase/clear for legacy states)."""

    values: jax.Array     # int32[n_shards, L+1] sharded over "shard"
    expiry_ms: jax.Array  # int32[n_shards, L+1] sharded over "shard"
    hits: Optional[jax.Array] = None  # int32[n_shards, L+1]


class ShardedBatchResult(NamedTuple):
    admitted: jax.Array   # bool[R] replicated
    hit_ok: jax.Array     # bool[n_shards, H_local]
    remaining: jax.Array  # int32[n_shards, H_local]
    ttl_ms: jax.Array     # int32[n_shards, H_local]


def make_mesh(devices=None, axis: str = "shard") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(devices, (axis,))


# -- pod-scale (multi-host) plumbing ------------------------------------------
#
# `jax.distributed.initialize()` + a pod-wide Mesh generalize every
# sharded kernel above across hosts (the multihost pjit pattern,
# SNIPPETS [3]): `jax.devices()` becomes the GLOBAL device list, the
# "shard" axis spans processes, and the collective-lean classification
# holds unchanged — a `coupled=False, has_global=False` launch lowers
# with ZERO cross-host collectives on the global mesh exactly as it
# does on ICI (tests/test_pod.py lints the HLO inside a live 2-process
# pod). Each host feeds only its addressable shards:
# `host_local_to_global` lifts host-local [n_local, H] staging rows
# into the global [n_total, H] array without materializing remote rows
# anywhere.


class PodInfo(NamedTuple):
    """The process's place in the pod (degenerate single-process values
    when `jax.distributed` was never initialized)."""

    process_id: int
    num_processes: int
    local_device_count: int
    global_device_count: int

    @property
    def multi_host(self) -> bool:
        return self.num_processes > 1


def initialize_pod(
    coordinator: str, num_processes: int, process_id: int
) -> PodInfo:
    """`jax.distributed.initialize()` with the CPU-pod affordance: on
    the host backend cross-process collectives need the gloo
    implementation (the default 'none' forms the pod but fails the
    first collective with "Multiprocess computations aren't
    implemented"), which is also how the 1/2/4-process bench and the
    2-process parity harness run a pod on one box. Idempotent: a
    second call in an already-initialized process just returns the
    live topology."""
    try:
        from jax._src.distributed import global_state as _dist_state
    except ImportError:  # pragma: no cover - newer jax layouts
        _dist_state = getattr(jax.distributed, "global_state", None)
    if (
        _dist_state is not None
        and getattr(_dist_state, "coordinator_address", None) is not None
    ):
        return pod_info()
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # older jaxlibs: TPU pods don't need the CPU collectives
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(num_processes),
        process_id=int(process_id),
    )
    return pod_info()


def pod_info() -> PodInfo:
    return PodInfo(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
    )


class PodMembership:
    """The pod's membership as a pure control-plane record (ISSUE 18).

    Under per-host meshes nothing about the device plane encodes which
    hosts are in the pod — that fact lives here: (hosts, host_id,
    peers, topology_epoch), flipped by the resize/join coordinator
    under commit and observed by subscribers (the warm standby's
    "am I live yet" signal, metrics). A flip is O(listeners): no jax
    re-form, no process restart — the property the sub-second join
    rides. `jax.process_count()`-style facts keep coming from the
    local runtime (always 1 process in per-host mode); THIS is the
    source of truth for pod-level membership."""

    def __init__(self, hosts: int = 1, host_id: int = 0,
                 peers=(), epoch: int = 0):
        self._lock = threading.Lock()
        self.hosts = int(hosts)
        self.host_id = int(host_id)
        self.peers = tuple(peers)
        self.epoch = int(epoch)
        self._listeners = []

    def subscribe(self, fn) -> None:
        """fn(membership) after every apply(); called outside the
        lock (a listener may read snapshot())."""
        with self._lock:
            self._listeners.append(fn)

    def apply(self, hosts: int, host_id: int, peers=(),
              epoch: Optional[int] = None) -> dict:
        with self._lock:
            self.hosts = int(hosts)
            self.host_id = int(host_id)
            self.peers = tuple(peers)
            self.epoch = (
                self.epoch + 1 if epoch is None else int(epoch)
            )
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(self)
            except Exception:  # a bad listener must not fail a commit
                pass
        return self.snapshot()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "hosts": self.hosts,
                "host_id": self.host_id,
                "peers": list(self.peers),
                "epoch": self.epoch,
            }

    @property
    def multi_host(self) -> bool:
        return self.hosts > 1


def make_host_mesh(axis: str = "shard") -> Mesh:
    """The PER-HOST mesh (ISSUE 18): this process's devices only, no
    matter how many hosts the pod has. Every pod member's device plane
    is one of these — membership is a pure control-plane fact
    (:class:`PodMembership` / routing.PodTopology) that the resize
    coordinator flips without re-forming any jax runtime, and
    cross-host reads ride the PeerLane (forwarded/bulk decisions) and
    the psum lane instead of cross-host device collectives. Identical
    geometry whether or not `jax.distributed` was ever initialized, so
    a warm standby can form (and compile against) this mesh long
    before it knows which pod it will join."""
    return Mesh(jax.local_devices(), (axis,))


def make_global_mesh(axis: str = "shard") -> Mesh:
    """The pod-wide mesh: every device of every process on one shard
    axis, ordered so each host's addressable devices form a contiguous
    block (global shard `g` belongs to host `g // local_device_count` —
    the contract routing.PodTopology encodes).

    Since ISSUE 18 this is the LEGACY formation: it requires the
    stop-the-world `jax.distributed` pod (fixed num_processes at boot),
    so the serving stack prefers per-host meshes (`make_host_mesh`)
    with the PeerLane for cross-host reads — the jax.distributed bench
    and parity harnesses are its remaining users."""
    procs = sorted(
        {d.process_index for d in jax.devices()}
    )
    ordered = [
        d
        for p in procs
        for d in sorted(
            (d for d in jax.devices() if d.process_index == p),
            key=lambda d: d.id,
        )
    ]
    return Mesh(ordered, (axis,))


def host_local_to_global(mesh: Mesh, arrays, axis: str = "shard"):
    """Lift host-local [n_local, ...] staging arrays into global
    [n_total, ...] arrays on a multi-host mesh (each host contributes
    only its addressable shards — remote rows are never materialized
    here). On a single-process mesh this is the plain sharded
    device_put the storage already performs."""
    sharding = batch_sharding(mesh, axis)
    if len(mesh.devices.flat) == len([
        d for d in mesh.devices.flat if d.process_index == jax.process_index()
    ]):
        return jax.device_put(tuple(arrays), sharding)
    from jax.experimental import multihost_utils

    spec = P(axis, None)
    return tuple(
        multihost_utils.host_local_array_to_global_array(a, mesh, spec)
        for a in arrays
    )


def pod_sync(tag: str = "pod") -> None:
    """DEVICE barrier across the pod's processes (no-op single-
    process): a psum over the global mesh, so it proves the device
    collectives themselves work. Must NOT be held while another thread
    needs the same devices — the CPU client serializes executions per
    device, so a concurrent local launch (e.g. a peer-lane forwarded
    decision) would deadlock against it; those phases use
    :func:`pod_barrier` instead."""
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


def pod_barrier(tag: str, timeout_ms: int = 120_000) -> None:
    """CONTROL-PLANE barrier across the pod's processes (no-op single-
    process): the coordination-service barrier of the distributed
    runtime — pure RPC, touches no device, so other threads keep
    launching freely while this one waits (the lockstep points of the
    pod drive, where the waiting host's lane thread must stay able to
    serve forwarded decisions)."""
    if jax.process_count() <= 1:
        return
    try:
        from jax._src.distributed import global_state
    except ImportError:  # pragma: no cover - newer jax layouts
        global_state = getattr(jax.distributed, "global_state", None)

    client = getattr(global_state, "client", None)
    if client is None:  # pragma: no cover - non-distributed fallback
        pod_sync(tag)
        return
    client.wait_at_barrier(tag, timeout_ms)


def batch_sharding(mesh: Mesh, axis: str = "shard") -> NamedSharding:
    """Sharding for [n_shards, H] batch arrays: device_put hit columns
    with this BEFORE the launch so each shard uploads only its own rows
    (a replicated upload costs n_shards x the bytes and leaves XLA to
    slice the local rows back out on device)."""
    return NamedSharding(mesh, P(axis, None))


def make_sharded_table(
    mesh: Mesh, local_capacity: int, axis: str = "shard"
) -> ShardedCounterState:
    n = mesh.shape[axis]
    sharding = NamedSharding(mesh, P(axis, None))
    make = lambda: jax.device_put(
        jnp.zeros((n, local_capacity + 1), jnp.int32), sharding
    )
    return ShardedCounterState(values=make(), expiry_ms=make(), hits=make())


def _local_step(values, expiry, hits, slots, deltas, maxes, windows,
                req_ids, fresh, bucket, is_global, now_ms, num_req, axis,
                global_region, coupled, has_global):
    """Per-device admission over the local shard; runs inside shard_map.

    Delegates to ops/kernel.py's shared ``check_and_update_core`` with two
    cross-device hooks, each compiled in ONLY when the batch needs it
    (module docstring, "Collective-lean variants"):

    - ``vote_combine`` (``coupled`` batches): requests may span devices;
      admission is all-or-nothing, so per-device verdicts AND across the
      mesh via ``pmin`` (devices without hits for a request vote True).
    - ``base_hook`` (``has_global`` batches): global counters occupy the
      same slot (< global_region) on every shard, each holding a
      per-device partial; the effective base is the psum of live partials
      over that compact region (the CRDT read-as-sum riding ICI).
      In-batch remote contributions are not visible until the next batch
      — bounded over-admission, as in the reference's distributed mode.
    """
    base_hook = None
    if has_global:
        live_partial = jnp.where(now_ms < expiry[:global_region],
                                 values[:global_region], 0)
        global_vals = lax.psum(live_partial, axis)
        s_glob = is_global[jnp.argsort(slots, stable=True)]

        def base_hook(v_local, s_slot):
            safe_idx = jnp.minimum(s_slot, global_region - 1)
            return jnp.where(s_glob, global_vals[safe_idx], v_local)

    vote_combine = None
    if coupled:
        def vote_combine(local_vote):
            return lax.pmin(local_vote.astype(jnp.int32), axis).astype(bool)

    return check_and_update_core(
        values, expiry, slots, deltas, maxes, windows, req_ids, fresh,
        bucket, now_ms, num_req, vote_combine=vote_combine,
        base_hook=base_hook, hits=hits,
    )


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis", "global_region", "coupled",
                     "has_global"),
    donate_argnums=(1,),
)
def sharded_check_and_update(
    mesh: Mesh,
    state: ShardedCounterState,
    slots: jax.Array,       # int32[n, H_local] owner-local slot per hit
    deltas: jax.Array,      # int32[n, H_local]
    maxes: jax.Array,       # int32[n, H_local]
    windows_ms: jax.Array,  # int32[n, H_local]
    req_ids: jax.Array,     # int32[n, H_local] request ids (see below)
    fresh: jax.Array,       # bool[n, H_local]
    bucket: jax.Array,      # bool[n, H_local] GCRA token-bucket hits
    is_global: jax.Array,   # bool[n, H_local] psum-replicated counter hits
    now_ms: jax.Array,      # int32 scalar
    axis: str = "shard",
    global_region: int = 1024,
    coupled: bool = True,
    has_global: bool = True,
) -> Tuple[ShardedCounterState, ShardedBatchResult]:
    """One fused multi-chip check-and-update step over the sharded table.

    ``coupled`` batches use GLOBAL request ids (< n*H, one id space mesh-
    wide) and return a replicated ``admitted[n*H]``; ``coupled=False``
    batches use SHARD-LOCAL ids (< H, every request's hits on one shard)
    and return ``admitted[n, H]`` sharded like the hit arrays — no
    cross-device collective at all when ``has_global`` is also False.

    Bucket hits are owner-sharded only (the host routes them like any
    exact counter; a TAT cell cannot be a psum global partial, so bucket
    counters in global namespaces stay on the host's exact path)."""
    n, H = slots.shape
    num_req = n * H if coupled else H

    def fn(values, expiry, hits, slots, deltas, maxes, windows, req_ids,
           fresh, bucket, is_global):
        (nv, ne, nh, admitted, ok, remaining, ttl) = _local_step(
            values[0], expiry[0], hits[0], slots[0], deltas[0], maxes[0],
            windows[0], req_ids[0], fresh[0], bucket[0], is_global[0],
            now_ms, num_req, axis, global_region, coupled, has_global,
        )
        if not coupled:
            admitted = admitted[None]  # [1, H]: this shard's verdicts
        return (
            nv[None], ne[None], nh[None], admitted, ok[None],
            remaining[None], ttl[None]
        )

    spec = P(axis, None)
    admitted_spec = P() if coupled else spec
    nv, ne, nh, admitted, ok, remaining, ttl = _shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec,) * 11,
        out_specs=(spec, spec, spec, admitted_spec, spec, spec, spec),
    )(state.values, state.expiry_ms, state.hits, slots, deltas,
      maxes, windows_ms, req_ids, fresh, bucket, is_global)
    return (
        ShardedCounterState(nv, ne, nh),
        ShardedBatchResult(admitted, ok, remaining, ttl),
    )


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis"), donate_argnums=(1,),
)
def sharded_clear_cells(
    mesh: Mesh,
    state: ShardedCounterState,
    slots: jax.Array,  # int32[n, K] per-shard slots to zero (pad: row L)
    axis: str = "shard",
) -> ShardedCounterState:
    """Zero (value, expiry) of per-shard cell lists IN PLACE (donated):
    the slot-release/eviction/delete path. Each shard scatters into its
    own rows — no collective, no full-table host round trip, and no
    un-donated ``.at[].set`` copy of the whole [n, L+1] table (which is
    what this replaces). Padding entries point at the scratch row L,
    which the kernel keeps zero anyway. Zeroing a GLOBAL slot everywhere
    = broadcast the slot list to every row of ``slots``. The hit
    accumulator clears with the cell (a recycled slot must not inherit
    the old occupant's traffic attribution)."""
    spec = P(axis, None)
    if state.hits is None:  # legacy state: no accumulator to clear

        def fn2(values, expiry, slots):
            return (
                values[0].at[slots[0]].set(0)[None],
                expiry[0].at[slots[0]].set(0)[None],
            )

        nv, ne = _shard_map(
            fn2, mesh=mesh, in_specs=(spec,) * 3, out_specs=(spec, spec),
        )(state.values, state.expiry_ms, slots)
        return ShardedCounterState(nv, ne)

    def fn(values, expiry, hits, slots):
        return (
            values[0].at[slots[0]].set(0)[None],
            expiry[0].at[slots[0]].set(0)[None],
            hits[0].at[slots[0]].set(0)[None],
        )

    nv, ne, nh = _shard_map(
        fn, mesh=mesh, in_specs=(spec,) * 4, out_specs=(spec, spec, spec),
    )(state.values, state.expiry_ms, state.hits, slots)
    return ShardedCounterState(nv, ne, nh)


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis"), donate_argnums=(1,),
)
def sharded_update(
    mesh: Mesh,
    state: ShardedCounterState,
    slots: jax.Array,       # int32[n, H_local]
    deltas: jax.Array,      # int32[n, H_local]
    windows_ms: jax.Array,  # int32[n, H_local]
    fresh: jax.Array,       # bool[n, H_local]
    bucket: jax.Array,      # bool[n, H_local]
    now_ms: jax.Array,      # int32 scalar
    axis: str = "shard",
) -> ShardedCounterState:
    """Unconditional batched increments over the sharded table (the
    Report/update and write-behind-authority path): per-shard saturating
    scatter-adds, no admission, no cross-device coupling — a global
    counter's delta simply lands in one shard's partial."""

    def fn(values, expiry, hits, slots, deltas, windows, fresh, bucket):
        nv, ne, nh = update_core(
            values[0], expiry[0], slots[0], deltas[0], windows[0], fresh[0],
            bucket[0], now_ms, hits=hits[0],
        )
        return nv[None], ne[None], nh[None]

    spec = P(axis, None)
    nv, ne, nh = _shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec,) * 8,
        out_specs=(spec, spec, spec),
    )(state.values, state.expiry_ms, state.hits, slots, deltas,
      windows_ms, fresh, bucket)
    return ShardedCounterState(nv, ne, nh)


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "k"), donate_argnums=(1,),
)
def sharded_drain_top_hits(
    mesh: Mesh,
    hits: jax.Array,  # int32[n, L+1] the state's accumulator (donated)
    k: int,
    axis: str = "shard",
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-shard read-and-reset of the hit accumulator: each shard's K
    hottest local slots, decided on its own device — no collective, and
    only 2*K ints per shard cross the host link. Returns (zeroed_hits,
    counts[n, k] descending per shard, slots[n, k]); count-0 entries
    are filler. The host merges shards (and sums the psum global
    region's per-shard counts) with full slot->counter attribution."""

    def fn(hits):
        counts, slots = lax.top_k(hits[0][:-1], k)
        return jnp.zeros_like(hits), counts[None], slots[None]

    spec = P(axis, None)
    return _shard_map(
        fn, mesh=mesh, in_specs=(spec,), out_specs=(spec, spec, spec),
    )(hits)


# -- lockstep pod psum lane (ISSUE 13) ----------------------------------------
#
# PR 10 pinned every global-limit namespace whole to one deterministic
# host: correct, but it re-creates the hot spot the pod exists to
# remove — 1-1/N of that namespace's traffic pays a peer hop and ONE
# host's device plane carries the whole namespace. The psum lane is the
# read-as-sum CRDT of the single-host global counters (module
# docstring, "Replicated global counters") lifted to host granularity:
# every host keeps an EXACT local partial per counter and decides
# against remote partials folded in by a lockstep exchange, so every
# ingress host answers locally and the namespace stops funneling.
#
# "Lockstep" is load-bearing: the exchange transport is collective
# (every pod host must run round k together, in round order), which is
# what makes the folded base a consistent pod-wide snapshot. The
# default transport rides the coordination-service KV store + barrier
# of the live `jax.distributed` runtime — pure control-plane RPC, no
# device program, because a device-collective exchange would deadlock
# against concurrent local launches exactly like `pod_sync` documents.
# The inaccuracy contract matches the device psum's: between exchange
# rounds a host cannot see deltas admitted remotely, so over-admission
# is bounded by one exchange interval per remote host (the reference's
# cached-Redis bound, redis_cached.rs:25-41).


class PodPsumLane:
    """Host-local exact partials + lockstep-folded remote base for
    global-namespace limits.

    ``configure(limits, global_namespaces)`` claims the namespaces this
    lane can serve (fixed-window only — a GCRA TAT cell cannot be a
    summed partial, the same exclusion the device psum region applies);
    the pod frontend then stops pinning them. The decision surface
    (``check_and_update`` / ``is_rate_limited`` / ``update_counters``)
    is synchronous and lock-cheap: one dict pass over local cells plus
    an int read of the folded remote vector — never an RPC.

    ``exchange()`` runs ONE lockstep round: publish my live partials,
    fold everyone else's. Every pod host must call it the same number
    of times in the same order (the transport is collective); the
    built-in pacing thread keeps hosts in lockstep by construction
    because each round's barrier waits for the slowest host.
    """

    #: remote partials fold into a fixed slot vector so the exchange
    #: payload is bounded; colliding keys MERGE their remote sums —
    #: strictly conservative (a merged base can only under-admit).
    DEFAULT_SLOTS = 2048

    def __init__(
        self,
        hosts: int,
        host_id: int,
        clock=time.time,
        slots: int = DEFAULT_SLOTS,
        cell_cap: int = 1 << 16,
        transport=None,
        barrier_timeout_ms: int = 30_000,
    ):
        from ..core.limiter import CheckResult
        from ..routing import counter_key
        from ..storage.expiring_value import ExpiringValue

        # bound once: the decision surface is registered as a hot
        # module (tracing-safety pass) — per-call `from x import y`
        # inside check_and_update/is_rate_limited would re-run a
        # sys.modules lookup on every psum-served request.
        self._CheckResult = CheckResult
        self._counter_key = counter_key
        self._ExpiringValue = ExpiringValue
        self.hosts = int(hosts)
        self.host_id = int(host_id)
        self._clock = clock
        self._slots = int(slots)
        self._cell_cap = int(cell_cap)
        self._barrier_timeout_ms = int(barrier_timeout_ms)
        #: namespaces (str) this lane serves; read lock-free by the
        #: frontend's `_psum_serves` (set replacement is atomic).
        self.namespaces: frozenset = frozenset()
        self._lock = threading.Lock()
        # counter key tuple -> ExpiringValue (this host's partial),
        # LRU-bounded like the in-memory qualified cache.
        from collections import OrderedDict

        from ..routing import stable_hash

        self._stable_hash = stable_hash
        self._cells: "OrderedDict" = OrderedDict()
        # key -> slot, filled at cell insertion and evicted with the
        # cell: the decision path and every _pack round then never
        # re-run repr+crc32 per key (the staging-pass hot spot
        # routing.RouteMemo documents) — _pack holds the decision lock,
        # so its per-cell cost is latency every psum decision pays.
        self._slot_memo: dict = {}
        # folded remote base (sum of OTHER hosts' live partials at the
        # last exchange round) per slot, with the latest expiry stamp —
        # reads treat an expired slot as 0, mirroring the device psum's
        # live_partial mask.
        self._remote_vals = np.zeros(self._slots, np.int64)
        self._remote_exp = np.zeros(self._slots, np.float64)
        self._transport = transport
        self.rounds = 0
        self.decisions = 0
        self.limited = 0
        self.exchanges = 0
        self._pacer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # set when the pacing thread dies on a failed exchange; a dead
        # lane must stay unclaimed across limits reloads (configure()
        # would otherwise re-claim namespaces nobody is folding).
        self._pacer_dead = False

    # -- configuration -------------------------------------------------------

    def configure(self, limits, global_namespaces) -> frozenset:
        """Claim the global namespaces every limit of which this lane
        can count (fixed-window policies only). Returns the served set;
        the caller pins the remainder as before."""
        if self._pacer_dead:
            self.namespaces = frozenset()
            return self.namespaces
        by_ns: dict = {}
        for limit in limits:
            by_ns.setdefault(str(limit.namespace), []).append(limit)
        served = frozenset(
            ns for ns in (str(n) for n in global_namespaces)
            if ns in by_ns and all(
                lim.policy == "fixed_window" for lim in by_ns[ns]
            )
        )
        self.namespaces = served
        return served

    # -- internals -----------------------------------------------------------

    def _slot_of(self, key: tuple) -> int:
        s = self._slot_memo.get(key)
        if s is None:
            s = self._stable_hash(key) % self._slots
        return s

    def _cell(self, key: tuple, window_s: int, now: float):
        ev = self._cells.get(key)
        if ev is None:
            # fresh window even on a pure check (the in-memory oracle's
            # in_memory.rs:122-127 semantics)
            ev = self._ExpiringValue(0, now + window_s)
            self._cells[key] = ev
            self._slot_memo[key] = (
                self._stable_hash(key) % self._slots
            )
            while len(self._cells) > self._cell_cap:
                evicted, _ = self._cells.popitem(last=False)
                self._slot_memo.pop(evicted, None)
        else:
            self._cells.move_to_end(key)
        return ev

    def _remote_live(self, key: tuple, now: float) -> int:
        s = self._slot_of(key)
        if now >= self._remote_exp[s]:
            return 0
        return int(self._remote_vals[s])

    # -- the decision surface (sync, called by PodFrontend) ------------------

    def check_and_update(
        self, counters, delta: int, load_counters: bool = False
    ):
        """Check-all-then-update-all over base+partial, the in-memory
        oracle's discipline (never over-admits locally; remote deltas
        since the last round are the bounded blind spot)."""
        CheckResult = self._CheckResult
        counter_key = self._counter_key
        now = self._clock()
        with self._lock:
            self.decisions += 1
            first_limited = None
            to_update = []
            # simple counters first, then qualified — the oracle's
            # first_limited order
            for qualified_pass in (False, True):
                for counter in counters:
                    if counter.is_qualified() is not qualified_pass:
                        continue
                    key = counter_key(counter)
                    ev = self._cell(key, counter.window_seconds, now)
                    value = ev.value_at(now) + self._remote_live(key, now)
                    over = value + delta > counter.max_value
                    if load_counters:
                        remaining = counter.max_value - (value + delta)
                        counter.remaining = max(remaining, 0)
                        counter.expires_in = ev.ttl(now)
                        if first_limited is None and remaining < 0:
                            first_limited = counter.limit.name
                    elif over:
                        self.limited += 1
                        return CheckResult(True, [], counter.limit.name)
                    to_update.append((ev, counter.window_seconds))
            if first_limited is not None:
                self.limited += 1
                return CheckResult(True, list(counters), first_limited)
            for ev, window in to_update:
                ev.update(delta, window, now)
        return CheckResult(False, list(counters) if load_counters else [],
                           None)

    def is_rate_limited(self, counters, delta: int):
        CheckResult = self._CheckResult
        counter_key = self._counter_key
        now = self._clock()
        with self._lock:
            self.decisions += 1
            for counter in counters:
                key = counter_key(counter)
                ev = self._cells.get(key)
                value = (ev.value_at(now) if ev is not None else 0) + \
                    self._remote_live(key, now)
                if value + delta > counter.max_value:
                    self.limited += 1
                    return CheckResult(True, [counter], counter.limit.name)
        return CheckResult(False, [], None)

    def update_counters(self, counters, delta: int) -> None:
        counter_key = self._counter_key
        now = self._clock()
        with self._lock:
            for counter in counters:
                key = counter_key(counter)
                ev = self._cell(key, counter.window_seconds, now)
                ev.update(delta, counter.window_seconds, now)

    # -- the lockstep exchange -----------------------------------------------

    def _pack(self, now: float) -> bytes:
        vals = np.zeros(self._slots, np.int64)
        exps = np.zeros(self._slots, np.float64)
        for key, ev in self._cells.items():
            v = ev.value_at(now)
            if v <= 0:
                continue
            s = self._slot_of(key)
            vals[s] += v
            if ev.expiry > exps[s]:
                exps[s] = ev.expiry
        return vals.tobytes() + exps.tobytes()

    def _unpack(self, payload: bytes):
        n = self._slots
        vals = np.frombuffer(payload[: n * 8], np.int64)
        exps = np.frombuffer(payload[n * 8:], np.float64)
        return vals, exps

    def _kv_transport(self, round_idx: int, payload: bytes):
        """The live-pod default: coordination-service KV + barrier of
        the `jax.distributed` runtime. Pure control-plane RPC — a
        device-collective exchange would deadlock against concurrent
        local launches (the pod_sync caveat)."""
        try:
            from jax._src.distributed import global_state
        except ImportError:  # pragma: no cover - newer jax layouts
            global_state = getattr(jax.distributed, "global_state", None)
        client = getattr(global_state, "client", None)
        if client is None:
            # A multi-host lane without a coordination client must FAIL
            # the round, not fabricate a healthy one: returning
            # all-None here would keep pod_psum_exchanges advancing
            # while every host folds a permanent-zero remote base —
            # exactly the N-times over-admission the pacer-death
            # unclaim path exists to prevent. Raising routes this
            # through that path (log + unclaim + stop pacing).
            raise RuntimeError(
                "pod psum lane: no jax.distributed coordination client "
                "for the KV exchange"
            )
        client.key_value_set(
            f"psum-lane/{round_idx}/{self.host_id}",
            base64.b64encode(payload).decode(),
        )
        client.wait_at_barrier(
            f"psum-lane-r{round_idx}", self._barrier_timeout_ms
        )
        # Reclaim my previous round's payload: passing round k's barrier
        # means every host completed round k-1 entirely (the lockstep
        # invariant), so the k-1 key can never be read again. Without
        # this the coordination service accrues ~slots*16B per host per
        # round forever (~1.4MB/s on an 8-host pod at the default
        # cadence) until the coordinator OOMs. Best-effort: a client
        # without key_value_delete just leaks like before.
        if round_idx > 0:
            delete = getattr(client, "key_value_delete", None)
            if delete is not None:
                try:
                    delete(f"psum-lane/{round_idx - 1}/{self.host_id}")
                except Exception:
                    pass
        out = []
        for h in range(self.hosts):
            if h == self.host_id:
                out.append(payload)
                continue
            raw = client.blocking_key_value_get(
                f"psum-lane/{round_idx}/{h}", self._barrier_timeout_ms
            )
            out.append(base64.b64decode(raw))
        return out

    def exchange(self) -> int:
        """One lockstep exchange round; returns the round count. Every
        pod host MUST call this the same number of times, in order (the
        transport is collective — the round's barrier paces all hosts
        to the slowest). Single-host pods fold nothing and stay
        exact."""
        now = self._clock()
        with self._lock:
            payload = self._pack(now)
            round_idx = self.rounds
        transport = self._transport or self._kv_transport
        payloads = transport(round_idx, payload)
        rv = np.zeros(self._slots, np.int64)
        re_ = np.zeros(self._slots, np.float64)
        for h, p in enumerate(payloads):
            if h == self.host_id or p is None:
                continue
            pv, pe = self._unpack(p)
            rv += pv
            np.maximum(re_, pe, out=re_)
        with self._lock:
            self._remote_vals = rv
            self._remote_exp = re_
            self.rounds = round_idx + 1
            self.exchanges += 1
        return self.rounds

    def start(self, interval_s: float = 0.25) -> None:
        """Pace lockstep rounds on a daemon thread: sleep, then
        exchange — the per-round barrier keeps every host's thread on
        the same round index (the fastest host waits). A host that
        stops responding times every peer's barrier out; each pacer
        then UNCLAIMS its namespaces before exiting, so the frontend's
        per-decision `_psum_serves` check reverts them to the pinned
        (exact, single-owner) path — a dead exchange must not leave N
        hosts each admitting the full limit on a base going stale."""
        if self._pacer is not None or self.hosts <= 1:
            return

        def run():
            while not self._stop.wait(interval_s):
                try:
                    self.exchange()
                except Exception:
                    if not self._stop.is_set():
                        import logging

                        logging.getLogger("limitador").warning(
                            "pod psum lane: exchange failed at round "
                            f"{self.rounds} (barrier timeout or peer "
                            "loss); unclaiming "
                            f"{len(self.namespaces)} namespaces — "
                            "they revert to the pinned path",
                            exc_info=True,
                        )
                    self._pacer_dead = True
                    self.namespaces = frozenset()
                    return

        self._pacer = threading.Thread(
            target=run, name="pod-psum-lane", daemon=True
        )
        self._pacer.start()

    def close(self) -> None:
        self._stop.set()

    # -- telemetry -----------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            live_remote = int(
                np.count_nonzero(
                    self._remote_vals
                    * (self._remote_exp > self._clock())
                )
            )
            return {
                "pod_psum_namespaces": len(self.namespaces),
                "pod_psum_decisions": self.decisions,
                "pod_psum_limited": self.limited,
                "pod_psum_exchanges": self.exchanges,
                "pod_psum_cells": len(self._cells),
                "pod_psum_remote_slots": live_remote,
            }


class PeerPsumTransport:
    """PeerLane-backed exchange for :class:`PodPsumLane` (ISSUE 18).

    Under per-host meshes there is no `jax.distributed` coordination
    client, so the psum lane's KV+barrier transport is unavailable —
    this transport replaces it with a push over the pod's gRPC peer
    lane. The contract loosens from barrier-lockstep to PACED: each
    host publishes its newest partials every round (``send(host_id,
    payload)`` — peering wires it to a ``kind:"psum_share"`` unary)
    and folds the newest payload it has RECEIVED from each peer
    (``receive()`` is the lane handler's delivery). A missing peer
    contributes None (the fold skips it), so a dead host costs
    staleness bounded by the pacing interval instead of stalling a
    pod-wide barrier.

    The pacer-death safety contract carries over: a peer whose
    payloads stop arriving ages out after ``stale_after_s`` (its
    partials fold as zero — bounded over-admission, the same blind
    spot a slow barrier round had), and when EVERY peer has been
    silent for ``dead_after_rounds`` consecutive rounds the transport
    raises, routing the lane through its unclaim path — N hosts must
    not each admit the full limit against a permanently-zero base."""

    def __init__(self, host_id: int, send, hosts: int = 1,
                 stale_after_s: float = 2.0,
                 dead_after_rounds: int = 8, clock=time.time):
        self.host_id = int(host_id)
        self.hosts = int(hosts)
        self._send = send
        self._stale_after_s = float(stale_after_s)
        self._dead_after_rounds = int(dead_after_rounds)
        self._clock = clock
        self._lock = threading.Lock()
        self._rx: dict = {}  # host -> (recv_monotonic, payload)
        self._silent_rounds = 0
        self.published = 0
        self.send_errors = 0

    def attach(self, hosts: int, host_id: Optional[int] = None) -> None:
        """Membership flip (resize/join commit): widen or shrink the
        fold without dropping already-received payloads."""
        with self._lock:
            self.hosts = int(hosts)
            if host_id is not None:
                self.host_id = int(host_id)
            self._silent_rounds = 0

    def receive(self, host: int, payload: bytes) -> None:
        """Lane delivery: a peer's published partials."""
        with self._lock:
            self._rx[int(host)] = (self._clock(), payload)

    def __call__(self, round_idx: int, payload: bytes):
        with self._lock:
            hosts, host_id = self.hosts, self.host_id
        for h in range(hosts):
            if h == host_id:
                continue
            try:
                self._send(h, payload)
            except Exception:
                self.send_errors += 1
        self.published += 1
        now = self._clock()
        out = []
        fresh_peers = 0
        with self._lock:
            for h in range(hosts):
                if h == host_id:
                    out.append(payload)
                    continue
                got = self._rx.get(h)
                if got is None or now - got[0] > self._stale_after_s:
                    out.append(None)
                else:
                    out.append(got[1])
                    fresh_peers += 1
            if hosts > 1 and fresh_peers == 0:
                self._silent_rounds += 1
            else:
                self._silent_rounds = 0
            if (hosts > 1
                    and self._silent_rounds >= self._dead_after_rounds):
                raise RuntimeError(
                    "peer psum transport: every peer silent for "
                    f"{self._silent_rounds} rounds; unclaiming"
                )
        return out
