"""Async micro-batcher: many concurrent checks -> one fused kernel launch.

The serving plane (gRPC/HTTP handlers) awaits ``AsyncTpuStorage`` methods;
concurrent ``check_and_update`` calls are coalesced into a single device
batch. This is where p99 <= 2ms is won or lost (SURVEY.md §7.4): the batcher
flushes on (a) batch full, (b) the oldest request exceeding ``max_delay``,
mirroring the size|interval|priority triple of the reference's write-behind
Batcher (/root/reference/limitador/src/storage/redis/counters_cache.rs:183-238)
— except here the flush IS the decision, not an async reconciliation, so
admission stays exact.

Two properties keep the event loop responsive and the device busy:

- **Off-loop dispatch**: every device interaction runs on dedicated
  executor threads; the asyncio loop only builds batches and resolves
  futures (the reference's tonic path is fully async the same way,
  envoy_rls/server.rs:238-272).
- **Double buffering**: when the storage exposes the
  ``begin_check_many``/``finish_check_many`` split (TpuStorage does),
  batch N+1 is assembled and its kernel launched while batch N's
  device->host transfer is still in flight; up to ``max_inflight``
  transfers overlap.

``UpdateBatcher`` gives the unconditional Report/update path the same
treatment: concurrent ``update_counter`` calls coalesce per counter into
one vectorized ``apply_deltas`` launch instead of a device round trip per
call (counters_cache.rs:143-247 is the reference blueprint).

**Chunked dispatch** (:class:`ChunkPlanner`): a monolithic 32k-hit flush
makes every request in it wait the full batch's device round trip. When
the storage exposes the begin/finish split, the flush is instead cut
into K sub-batches dispatched through the same ``max_inflight`` window:
chunk i+1's staging and upload overlap chunk i's device execution
(double buffering — the sharded/batched extension of the single-device
prefetch trick bench.py measures), so occupancy holds while the
queue-excluded device round trip a request observes drops toward
``T/K``. K is auto-tuned from the device-plane queue-wait signal the
admission layer measures: chunks are sized so one sub-batch's device
time tracks the 2ms latency budget, tightening to half-budget once
queue wait alone has eaten it — decisions start flowing sooner while
the staging/compute overlap keeps throughput (ChunkPlanner docstring
has the measurements). ``dispatch_chunk`` pins a size (0 = monolithic)
for benchmarking and regression bisection.

Within a batch, requests keep their enqueue order and the kernel decides
admission exactly as if they were processed serially; all hit-building and
result-decoding semantics live in ``TpuStorage.check_many`` — the batcher
only owns the coalescing.

On sharded storage, a flush's staging additionally rides the native
per-shard partition pass when the hostpath library is loaded
(``hp_partition_positions`` via storage.py ``_partition_positions``:
one O(n) GIL-free C sweep replacing the argsort) — the MicroBatcher
flush path's slice of the ISSUE-5 zero-Python hot lane.
"""

from __future__ import annotations

import asyncio
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from ..core.counter import Counter
from ..core.limit import Limit
from ..observability.device_plane import (
    DeviceStatsRecorder,
    current_request_id,
)
from ..observability.metrics_layer import installed as _metrics_layer_installed
from ..observability.tracing import device_batch_span
from ..storage.base import (
    AsyncCounterStorage,
    Authorization,
    StorageError,
    require_nonnegative_delta,
)
from .storage import TpuStorage, _Request, _bucket

__all__ = [
    "ChunkPlanner",
    "MicroBatcher",
    "UpdateBatcher",
    "AsyncTpuStorage",
    "METRIC_FAMILIES",
]

#: metric families this subsystem owns (cross-checked against
#: observability/metrics.py by tools/lint.py's registry lint): how
#: flushes split into pipelined sub-batch launches.
METRIC_FAMILIES = ("dispatch_chunk_hits", "dispatch_chunk_splits")


class ChunkPlanner:
    """Sizes pipelined sub-batches for one dispatch lane.

    ``dispatch_chunk``: ``None`` = auto, ``0`` = monolithic (never
    split), ``> 0`` = fixed hits per chunk. Auto mode sizes chunks so
    ONE sub-batch's device time tracks ``target_s`` (default 2ms — the
    north-star p99 budget the queue-excluded datastore latency is judged
    against), using an EWMA of observed device seconds per hit. The
    queue-wait signal (the admission plane's AIMD estimate when one is
    attached) modulates the target: once queueing alone has eaten the
    budget, the device slice tightens to half-budget so decisions start
    flowing sooner — measured on the 2-core CI box this cut datastore
    p50 16.3->6.7ms and p99 21.6->15.4ms while IMPROVING throughput
    (7.3k->7.9k/s; staging overlaps compute, so smaller launches cost
    almost nothing). Under light load (queue wait inside the budget) a
    full-budget slice minimizes launch count. Shared by the MicroBatcher
    and both compiled pipelines; the EWMA update races across collect
    threads benignly (floats, last-write-wins)."""

    MIN_CHUNK = 512
    MAX_SPLITS = 16

    def __init__(self, dispatch_chunk: Optional[int] = None,
                 target_s: float = 0.002):
        self.dispatch_chunk = dispatch_chunk
        self.target_s = float(target_s)
        self._per_hit_s = 0.0  # EWMA device_sync seconds per hit

    #: retarget() bounds — the capacity controller may steer target_s
    #: only inside this envelope (seconds)
    MIN_TARGET_S = 0.0005
    MAX_TARGET_S = 0.008

    def retarget(self, target_s: float) -> float:
        """Move the auto-mode device-time target (the capacity
        controller's chunk knob, ISSUE 20). Clamped to
        ``[MIN_TARGET_S, MAX_TARGET_S]``; a fixed ``dispatch_chunk``
        still wins in :meth:`chunk_hits`. Returns the applied target
        in seconds."""
        self.target_s = min(
            max(float(target_s), self.MIN_TARGET_S), self.MAX_TARGET_S
        )
        return self.target_s

    def observe(self, device_s: float, hits: int) -> None:
        """Feed one finished launch's device_sync time."""
        if hits <= 0 or device_s <= 0.0:
            return
        per = device_s / hits
        self._per_hit_s = (
            per if self._per_hit_s == 0.0
            else 0.8 * self._per_hit_s + 0.2 * per
        )

    def chunk_hits(self, queue_wait_s: float = 0.0) -> int:
        """Target hits per chunk; 0 = dispatch monolithically."""
        fixed = self.dispatch_chunk
        if fixed is not None:
            return max(int(fixed), 0)
        per = self._per_hit_s
        if per <= 0.0:
            return 0  # no device-time signal yet: stay monolithic
        target = self.target_s
        if queue_wait_s > target:
            # The queue has already eaten the latency budget: tighten
            # the device slice to half-budget so decisions start
            # flowing sooner instead of parking behind one big launch.
            target = target / 2
        # Quantized to the kernel's power-of-two hit buckets: chunk sizes
        # drifting with the EWMA would otherwise keep minting new XLA
        # programs (one compile stall each) instead of reusing a handful.
        return _bucket(max(int(target / per), self.MIN_CHUNK))

    def split(self, sizes, queue_wait_s: float = 0.0):
        """Partition a flush into chunk index ranges. ``sizes`` holds
        per-item hit counts in flush order; returns ``[(lo, hi), ...]``
        covering every item. A flush under 2 chunks' worth of hits stays
        monolithic (a tiny tail launch costs more than it hides), and a
        flush never splits past MAX_SPLITS launches."""
        n_items = len(sizes)
        chunk = self.chunk_hits(queue_wait_s)
        total = sum(sizes)
        if chunk <= 0 or total < 2 * chunk or n_items < 2:
            return [(0, n_items)]
        chunk = max(chunk, (total + self.MAX_SPLITS - 1) // self.MAX_SPLITS)
        ranges = []
        lo = 0
        acc = 0
        for i, size in enumerate(sizes):
            acc += size
            if acc >= chunk and i + 1 < n_items:
                ranges.append((lo, i + 1))
                lo, acc = i + 1, 0
        ranges.append((lo, n_items))
        if len(ranges) > 1 and acc < min(self.MIN_CHUNK, chunk):
            # A sub-MIN tail launch costs more than it hides (and mints
            # an extra small-bucket XLA program): fold it into the
            # previous chunk.
            (lo2, _hi2), (lo1, hi1) = ranges[-2], ranges[-1]
            ranges[-2:] = [(lo2, hi1)]
        return ranges


def chunk_queue_wait(admission, oldest_enqueue: float,
                     t_flush: float) -> float:
    """Queue-wait signal feeding a ChunkPlanner, shared by the three
    dispatch lanes (MicroBatcher and both compiled pipelines): the
    admission plane's AIMD estimate when one is attached (the signal it
    already maintains from record_flush), else this flush's oldest
    wait."""
    if admission is not None:
        try:
            return admission.overload.queue_wait_estimate()
        except Exception:
            pass
    return t_flush - oldest_enqueue


def _latency_hists(metrics) -> list:
    """Histograms a device batch round trip should be observed into.
    The queue-excluded device view always lands in
    ``datastore_device_latency`` when the sink provides it; without a
    MetricsLayer installed (bare-library embedding — the server installs
    one) the sample also feeds ``datastore_latency`` directly, since no
    span aggregation is there to populate it."""
    hists = []
    dev = getattr(metrics, "datastore_device_latency", None)
    if dev is not None:
        hists.append(dev)
    if _metrics_layer_installed() is None:
        hists.append(metrics.datastore_latency)
    return hists


def _timed_call(fn, arg):
    """(fn(arg), t_start, t_end) — phase timing across an executor hop:
    t_start - caller's submit time is the executor handoff ("dispatch"),
    t_end - t_start is the call itself."""
    t_start = time.perf_counter()
    out = fn(arg)
    return out, t_start, time.perf_counter()


class MicroBatcher:
    def __init__(
        self,
        storage: TpuStorage,
        max_batch_hits: int = 8192,
        max_delay: float = 0.0005,
        max_inflight: int = 2,
        dispatch_chunk: Optional[int] = None,
    ):
        self.storage = storage
        self.max_batch_hits = max_batch_hits
        self.max_delay = max_delay
        self.max_inflight = max_inflight
        # Pipelined sub-batch execution (module docstring): None = auto
        # (sized from the queue-wait signal), 0 = monolithic, >0 fixed.
        self.chunk_planner = ChunkPlanner(dispatch_chunk)
        self._pending: List[tuple] = []  # (_Request, Future)
        self._pending_hits = 0
        self._wakeup: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        # Dispatch thread: serializes begin_check_many in batch order.
        # Collect threads: device->host transfers, may overlap.
        self._dispatch_pool = ThreadPoolExecutor(
            1, thread_name_prefix="tpu-dispatch"
        )
        self._collect_pool = ThreadPoolExecutor(
            max_inflight, thread_name_prefix="tpu-collect"
        )
        self._finishers: set = set()
        self.flush_sizes: List[int] = []  # drained by library_stats
        # When set, per-request datastore latency (the device batch round
        # trip each request waited on, queue/linger excluded) is observed
        # here — the busy-time semantics of the reference's MetricsLayer
        # (metrics.rs:100-211) instead of handler wall clock.
        self.metrics = None
        # Device-plane telemetry sink (queue waits, fill ratios, flush
        # reasons, phase timings, flight recorder). None until
        # set_metrics attaches one: every instrumentation site below is
        # gated on this single check, so a detached batcher pays nothing
        # per decision (the tracing.py _enabled discipline).
        self.recorder = None
        # Admission controller (admission/controller.py). None = no
        # breaker feed, no failover drain — same zero-cost-when-detached
        # discipline as the recorder.
        self.admission = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # Dispatched-but-unfinished batches, so a breaker trip can fail
        # their futures instead of leaving them parked on a dead plane.
        self._inflight_batches: Dict[int, list] = {}
        self._batch_seq = 0

    def _observe_batch(self, n_requests: int, dt: float) -> None:
        if self.metrics is not None:
            for hist in _latency_hists(self.metrics):
                observe = hist.observe
                for _ in range(n_requests):
                    observe(dt)

    def _ensure_started(self) -> None:
        if self._task is None or self._task.done():
            self._wakeup = asyncio.Event()
            self._loop = asyncio.get_running_loop()
            self._task = self._loop.create_task(self._run())

    def fail_over_queued(self, decider, exc) -> None:
        """Admission-plane breaker trip: every QUEUED request gets an
        immediate host-side decision through ``decider(counters, delta,
        load) -> Authorization``; dispatched-but-unfinished batches fail
        with ``exc`` (transient — their kernel may already have run, so
        re-deciding them host-side would double-count). Thread-safe:
        the trip listener can fire from a collect thread."""
        loop = self._loop
        if loop is None or loop.is_closed():
            return

        def _drain():
            pending, self._pending = self._pending, []
            self._pending_hits = 0
            for request, future, _t, _rid in pending:
                if future.done():
                    continue
                try:
                    future.set_result(
                        decider(request.ordered, request.delta, request.load)
                    )
                except Exception as dexc:
                    future.set_exception(dexc)
            for batch in list(self._inflight_batches.values()):
                self._fail(batch, exc)

        loop.call_soon_threadsafe(_drain)

    async def submit(
        self, counters: List[Counter], delta: int, load: bool
    ) -> Authorization:
        """Enqueue one request; resolves when its batch has been decided."""
        require_nonnegative_delta(delta)
        self._ensure_started()
        future = asyncio.get_running_loop().create_future()
        request = _Request(counters, delta, load)
        rid = current_request_id() if self.recorder is not None else None
        self._pending.append((request, future, time.perf_counter(), rid))
        self._pending_hits += len(request.ordered)
        self._wakeup.set()
        return await future

    @staticmethod
    def _fail(batch, exc) -> None:
        for _r, future, _t, _rid in batch:
            if not future.done():
                future.set_exception(exc)

    @staticmethod
    def _resolve(batch, auths) -> None:
        for (_r, future, _t, _rid), auth in zip(batch, auths):
            if not future.done():
                future.set_result(auth)

    @staticmethod
    def _record_batch(rec, batch, batch_id, t_flush, phases) -> None:
        rec.record_batch(
            (
                (t_enq, rid,
                 request.ordered[0].namespace if request.ordered else None)
                for request, _future, t_enq, rid in batch
            ),
            batch_id, t_flush, phases,
        )

    async def _finish_inflight(
        self, batch, handle, finish, sem, loop, t0, t_flush, batch_id,
        phases, seq, token, n_hits,
    ):
        adm = self.admission
        try:
            with device_batch_span(batch_id, len(batch)) as span_phases:
                auths, t_fin, t_done = await loop.run_in_executor(
                    self._collect_pool, _timed_call, finish, handle
                )
                phases["device_sync"] = t_done - t_fin
                self.chunk_planner.observe(phases["device_sync"], n_hits)
                self._observe_batch(len(batch), time.perf_counter() - t0)
                self._resolve(batch, auths)
                phases["unpack"] = time.perf_counter() - t_done
                span_phases(phases)
                rec = self.recorder
                if rec is not None:
                    self._record_batch(rec, batch, batch_id, t_flush, phases)
            if adm is not None:
                adm.breaker.batch_finished(token)
        except Exception as exc:
            self._fail(batch, exc)
            if adm is not None:
                adm.breaker.batch_finished(token, exc)
        finally:
            self._inflight_batches.pop(seq, None)
            sem.release()


    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        begin = getattr(self.storage, "begin_check_many", None)
        finish = getattr(self.storage, "finish_check_many", None)
        pipelined = begin is not None and finish is not None
        sem = asyncio.Semaphore(self.max_inflight)
        while not self._closed:
            while not self._pending:
                self._wakeup.clear()
                if self._closed:
                    return
                try:
                    await asyncio.wait_for(self._wakeup.wait(), timeout=1.0)
                except asyncio.TimeoutError:
                    if self._closed:
                        return
            if self._pending_hits < self.max_batch_hits:
                # Linger briefly to let concurrent requests coalesce.
                await asyncio.sleep(self.max_delay)
            if pipelined:
                # Acquire the inflight slot BEFORE taking the batch:
                # under device backpressure requests keep coalescing in
                # _pending — where an admission-plane failover can still
                # drain them — instead of riding in a local batch
                # nothing can reach while this coroutine waits.
                await sem.acquire()
            # A failover drain may have emptied the queue during the
            # linger / slot wait: nothing to flush.
            if not self._pending:
                if pipelined:
                    sem.release()
                continue
            # The linger may have filled the batch past the size trigger:
            # classify by what actually releases the flush.
            reason = (
                "size" if self._pending_hits >= self.max_batch_hits
                else "deadline"
            )
            batch = self._pending
            flush_hits = self._pending_hits
            self._pending = []
            self._pending_hits = 0
            requests = [r for r, _f, _t, _rid in batch]
            # Recorded in COUNTERS (hits), matching the shared
            # batcher_flush_size histogram's unit.
            self.flush_sizes.append(flush_hits)
            del self.flush_sizes[:-1000]
            rec = self.recorder
            t_flush = time.perf_counter()
            batch_id = 0
            if rec is not None:
                batch_id = rec.next_batch_id()
                rec.record_flush(
                    reason, flush_hits / self.max_batch_hits,
                    [t_flush - t for _r, _f, t, _rid in batch],
                )
            adm = self.admission
            if pipelined:
                # Chunked pipelined dispatch: the flush splits into K
                # sub-batches riding the same inflight window, so chunk
                # i+1 stages/uploads while chunk i executes and a
                # request's device round trip is its CHUNK's, not the
                # whole flush's. The first chunk uses the slot acquired
                # above; each further chunk takes its own.
                ranges = self.chunk_planner.split(
                    [len(r.ordered) for r in requests],
                    chunk_queue_wait(adm, batch[0][2], t_flush),
                )
                rec = self.recorder
                if rec is not None:
                    rec.record_chunks([
                        sum(len(r.ordered) for r in requests[lo:hi])
                        for lo, hi in ranges
                    ])
                # Every chunk registers as in-flight BEFORE any await:
                # an admission-plane breaker trip must be able to fail
                # chunks still waiting on the inflight window — they are
                # out of _pending, so _inflight_batches is the only
                # place the failover drain can reach them (the same
                # whole-flush visibility the monolithic path had).
                chunk_seqs = []
                for lo, hi in ranges:
                    self._batch_seq += 1
                    self._inflight_batches[self._batch_seq] = batch[lo:hi]
                    chunk_seqs.append(self._batch_seq)
                first_chunk = True
                failed = None
                for idx, ((lo, hi), seq) in enumerate(
                    zip(ranges, chunk_seqs)
                ):
                    sub = batch[lo:hi]
                    if failed is not None:
                        # A begin failure is plane-wide (the launch never
                        # made it to the device): fail the rest of the
                        # flush the way a monolithic dispatch would have.
                        self._inflight_batches.pop(seq, None)
                        self._fail(sub, failed)
                        continue
                    if not first_chunk:
                        try:
                            await sem.acquire()
                        except BaseException as exc:
                            # Cancellation mid-flush must not strand the
                            # chunks still waiting on the window.
                            for (l2, h2), s2 in zip(
                                ranges[idx:], chunk_seqs[idx:]
                            ):
                                self._inflight_batches.pop(s2, None)
                                self._fail(batch[l2:h2], exc)
                            raise
                    first_chunk = False
                    sub_requests = requests[lo:hi]
                    n_hits = sum(len(r.ordered) for r in sub_requests)
                    token = (
                        adm.breaker.batch_started() if adm is not None else 0
                    )
                    t0 = time.perf_counter()
                    try:
                        handle, t_begin, t_launch = (
                            await loop.run_in_executor(
                                self._dispatch_pool, _timed_call, begin,
                                sub_requests,
                            )
                        )
                    except Exception as exc:
                        sem.release()
                        self._inflight_batches.pop(seq, None)
                        self._fail(sub, exc)
                        if adm is not None:
                            adm.breaker.batch_finished(token, exc)
                        failed = exc
                        continue
                    phases = {
                        "dispatch": t_begin - t0,
                        "host_stage": t_launch - t_begin,
                    }
                    t = loop.create_task(
                        self._finish_inflight(
                            sub, handle, finish, sem, loop, t0, t_flush,
                            batch_id, phases, seq, token, n_hits,
                        )
                    )
                    self._finishers.add(t)
                    t.add_done_callback(self._finishers.discard)
            else:
                self._batch_seq += 1
                seq = self._batch_seq
                self._inflight_batches[seq] = batch
                token = adm.breaker.batch_started() if adm is not None else 0
                t0 = time.perf_counter()
                try:
                    with device_batch_span(
                        batch_id, len(batch)
                    ) as span_phases:
                        auths, t_begin, t_done = await loop.run_in_executor(
                            self._dispatch_pool, _timed_call,
                            self.storage.check_many, requests,
                        )
                        self._observe_batch(
                            len(batch), time.perf_counter() - t0
                        )
                        self._resolve(batch, auths)
                        # check_many fuses staging, launch and the device
                        # wait in one call: no host_stage/device_sync split
                        # to report on this path.
                        phases = {
                            "dispatch": t_begin - t0,
                            "device_sync": t_done - t_begin,
                            "unpack": time.perf_counter() - t_done,
                        }
                        span_phases(phases)
                        if rec is not None:
                            self._record_batch(
                                rec, batch, batch_id, t_flush, phases
                            )
                    if adm is not None:
                        adm.breaker.batch_finished(token)
                except Exception as exc:
                    self._fail(batch, exc)
                    if adm is not None:
                        adm.breaker.batch_finished(token, exc)
                finally:
                    self._inflight_batches.pop(seq, None)

    async def close(self) -> None:
        self._closed = True
        if self._wakeup is not None:
            self._wakeup.set()
        if self._task is not None:
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._finishers:
            await asyncio.gather(*list(self._finishers), return_exceptions=True)
        # Requests that slipped in while the last flush was off-loop would
        # otherwise await forever: decide them in one final batch.
        if self._pending:
            batch, self._pending = self._pending, []
            flush_hits = self._pending_hits
            self._pending_hits = 0
            rec = self.recorder
            if rec is not None:
                t_now = time.perf_counter()
                rec.record_flush(
                    "shutdown", flush_hits / self.max_batch_hits,
                    [t_now - t for _r, _f, t, _rid in batch],
                )
            try:
                self._resolve(
                    batch,
                    self.storage.check_many(
                        [r for r, _f, _t, _rid in batch]
                    ),
                )
            except Exception as exc:
                self._fail(batch, exc)
        self._dispatch_pool.shutdown(wait=False)
        self._collect_pool.shutdown(wait=False)


class UpdateBatcher:
    """Coalesces unconditional increments (the Kuadrant Report path /
    ``update_counter``) into vectorized ``apply_deltas`` launches: deltas
    sum per counter identity, one device call per flush instead of one per
    request."""

    def __init__(
        self,
        storage,
        max_batch: int = 4096,
        max_delay: float = 0.0005,
    ):
        self.storage = storage
        self.max_batch = max_batch
        self.max_delay = max_delay
        self._pending: Dict[Counter, int] = {}
        self._waiters: List[asyncio.Future] = []
        self._wakeup: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        self._pool = ThreadPoolExecutor(1, thread_name_prefix="tpu-update")
        self.metrics = None
        # Device-plane telemetry sink; None = detached, zero hot-path cost
        # (the MicroBatcher discipline).
        self.recorder = None
        # Admission controller; feeds the device-plane breaker and lets
        # a trip drain queued updates into the failover journal.
        self.admission = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # Waiters of the flush currently inside _apply on the executor,
        # so a breaker trip can settle them off a dead plane (the
        # MicroBatcher._inflight_batches pattern for the update path).
        self._inflight_waiters: Dict[int, list] = {}
        self._flush_seq = 0

    def _ensure_started(self) -> None:
        if self._task is None or self._task.done():
            self._wakeup = asyncio.Event()
            self._loop = asyncio.get_running_loop()
            self._task = self._loop.create_task(self._run())

    def fail_over_queued(self, apply_fn, exc=None) -> None:
        """Breaker trip: journal every queued (counter, delta) through
        ``apply_fn`` (the failover store) and settle the waiters; the
        flush already inside ``_apply`` on the dead plane settles with
        ``exc`` (its deltas may land when the device unwedges —
        journaling them too would double-count). No Report-path caller
        waits on the dead plane. Thread-safe."""
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        stuck_exc = exc or StorageError(
            "device plane failed over", transient=True
        )

        def _drain():
            items, waiters = self._swap()
            try:
                for counter, delta in items:
                    apply_fn(counter, delta)
            except Exception as dexc:
                self._settle(waiters, dexc)
            else:
                self._settle(waiters, None)
            for stuck in list(self._inflight_waiters.values()):
                self._settle(stuck, stuck_exc)

        loop.call_soon_threadsafe(_drain)

    async def submit(self, counter: Counter, delta: int) -> None:
        # Reject before coalescing: a negative delta inside the batch
        # would fail the whole apply and drop other requests' updates.
        require_nonnegative_delta(delta)
        self._ensure_started()
        future = asyncio.get_running_loop().create_future()
        self._pending[counter] = self._pending.get(counter, 0) + int(delta)
        self._waiters.append((future, time.perf_counter()))
        self._wakeup.set()
        await future

    def _apply(self, items: List[Tuple[Counter, int]]) -> None:
        apply = getattr(self.storage, "apply_deltas", None)
        if apply is not None:
            apply(items)
            return
        for counter, delta in items:
            self.storage.update_counter(counter, delta)

    @staticmethod
    def _settle(waiters, exc) -> None:
        for future, _t in waiters:
            if future.done():
                continue
            if exc is not None:
                future.set_exception(exc)
            else:
                future.set_result(None)

    def _record_flush(self, reason: str, n_counters: int, waiters) -> None:
        rec = self.recorder
        if rec is not None:
            t_now = time.perf_counter()
            rec.record_flush(
                reason, n_counters / self.max_batch,
                [t_now - t for _f, t in waiters],
                batcher="update",
            )

    def _swap(self):
        items = list(self._pending.items())
        waiters = self._waiters
        self._pending = {}
        self._waiters = []
        return items, waiters

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._closed:
            while not self._pending:
                self._wakeup.clear()
                if self._closed:
                    return
                try:
                    await asyncio.wait_for(self._wakeup.wait(), timeout=1.0)
                except asyncio.TimeoutError:
                    if self._closed:
                        return
            if len(self._pending) < self.max_batch:
                await asyncio.sleep(self.max_delay)
            if not self._pending:
                continue  # a failover drain emptied it during the linger
            reason = (
                "size" if len(self._pending) >= self.max_batch
                else "deadline"
            )
            items, waiters = self._swap()
            self._record_flush(reason, len(items), waiters)
            adm = self.admission
            token = adm.breaker.batch_started() if adm is not None else 0
            self._flush_seq += 1
            seq = self._flush_seq
            self._inflight_waiters[seq] = waiters
            t0 = time.perf_counter()
            try:
                await loop.run_in_executor(self._pool, self._apply, items)
            except Exception as exc:
                if adm is not None:
                    adm.breaker.batch_finished(token, exc)
                self._settle(waiters, exc)
            else:
                if adm is not None:
                    adm.breaker.batch_finished(token)
                if self.metrics is not None:
                    dt = time.perf_counter() - t0
                    for hist in _latency_hists(self.metrics):
                        for _ in waiters:
                            hist.observe(dt)
                self._settle(waiters, None)
            finally:
                self._inflight_waiters.pop(seq, None)

    async def close(self) -> None:
        self._closed = True
        if self._wakeup is not None:
            self._wakeup.set()
        if self._task is not None:
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._pending:
            items, waiters = self._swap()
            self._record_flush("shutdown", len(items), waiters)
            try:
                self._apply(items)
            except Exception as exc:
                self._settle(waiters, exc)
            else:
                self._settle(waiters, None)
        self._pool.shutdown(wait=False)


class AsyncTpuStorage(AsyncCounterStorage):
    """AsyncCounterStorage over TpuStorage + MicroBatcher: the hot
    check_and_update path batches, the Report/update path batches through
    ``UpdateBatcher``; admin operations delegate inline.

    Serving shards: the batchers are PER EVENT LOOP — a MicroBatcher's
    queue, wakeup event and flush task are loop-affine, so each serving
    loop (thread) gets its own pair, all feeding the one thread-safe
    device storage behind them (kernel launches serialize under the
    storage lock in call order). The first loop to submit binds the
    eagerly-created default pair (``self.batcher`` /
    ``self.update_batcher``), keeping the single-loop embedding
    unchanged."""

    reports_datastore_latency = False

    @property
    def supports_token_bucket(self) -> bool:
        # Defer to the wrapped storage: plain TpuStorage counts buckets
        # on its exact host path (True); the replicated subclass rejects
        # them (its gossip floods are fixed-window-shaped).
        return getattr(self.inner, "supports_token_bucket", False)

    def __init__(
        self,
        storage: Optional[TpuStorage] = None,
        max_batch_hits: int = 8192,
        max_delay: float = 0.0005,
        dispatch_chunk: Optional[int] = None,
        **kwargs,
    ):
        self.inner = storage or TpuStorage(**kwargs)
        self.batcher = MicroBatcher(
            self.inner, max_batch_hits, max_delay,
            dispatch_chunk=dispatch_chunk,
        )
        self.update_batcher = UpdateBatcher(self.inner, max_delay=max_delay)
        self._batcher_args = (max_batch_hits, max_delay, dispatch_chunk)
        self._metrics = None
        # loop -> (MicroBatcher, UpdateBatcher); the first loop gets the
        # default pair above. The default pair binds AT MOST once — its
        # wakeup event / run task are loop-affine, so after its loop
        # dies later loops get fresh pairs instead of a rebind.
        self._loop_batchers: dict = {}
        self._default_bound = False
        self._shards_lock = threading.Lock()
        self.recorder: Optional[DeviceStatsRecorder] = None
        # Admission controller (admission/controller.py); None = the
        # pre-admission-plane behavior, zero hot-path cost.
        self.admission = None

    def _batcher_pairs(self) -> list:
        """Every live (check, update) batcher pair, the default pair
        included even before a loop binds it."""
        pairs = list(self._loop_batchers.values())
        if not any(b is self.batcher for b, _u in pairs):
            pairs.append((self.batcher, self.update_batcher))
        return pairs

    def _batchers_for_loop(self):
        loop = asyncio.get_running_loop()
        pair = self._loop_batchers.get(loop)
        if pair is not None:
            return pair
        with self._shards_lock:
            pair = self._loop_batchers.get(loop)
            if pair is None:
                # Prune pairs whose loop died (new-loop-per-call
                # embeddings would otherwise leak a batcher pair per
                # dead loop for the storage's lifetime). The default
                # pair is kept: close() owns it.
                for dead in [
                    l for l in self._loop_batchers if l.is_closed()
                ]:
                    mb, ub = self._loop_batchers.pop(dead)
                    if mb is not self.batcher:
                        mb._dispatch_pool.shutdown(wait=False)
                        mb._collect_pool.shutdown(wait=False)
                        ub._pool.shutdown(wait=False)
                if not self._default_bound:
                    # first loop ever binds the default pair
                    self._default_bound = True
                    pair = (self.batcher, self.update_batcher)
                else:
                    max_batch_hits, max_delay, dispatch_chunk = (
                        self._batcher_args
                    )
                    mb = MicroBatcher(
                        self.inner, max_batch_hits, max_delay,
                        dispatch_chunk=dispatch_chunk,
                    )
                    ub = UpdateBatcher(self.inner, max_delay=max_delay)
                    mb.metrics = self._metrics
                    ub.metrics = self._metrics
                    mb.recorder = self.recorder
                    ub.recorder = self.recorder
                    mb.admission = self.admission
                    ub.admission = self.admission
                    pair = (mb, ub)
                self._loop_batchers[loop] = pair
            return pair

    def set_admission(self, controller) -> None:
        """Put this storage under an admission controller: the check
        path consults its breaker (failing over to the host oracle when
        open), and the batchers feed it batch outcomes."""
        self.admission = controller
        for mb, ub in self._batcher_pairs():
            mb.admission = controller
            ub.admission = controller
        controller.bind_storage(self)

    def fail_over_queued(self, decider, exc) -> None:
        """Breaker trip fan-out (called by the controller's transition
        listener): drain every shard's batcher queues off the dead
        plane."""
        adm = self.admission
        for mb, ub in self._batcher_pairs():
            mb.fail_over_queued(decider, exc)
            if adm is not None:
                ub.fail_over_queued(adm.failover_update_counter, exc)

    def set_metrics(self, metrics) -> None:
        """Have the batchers observe per-request datastore latency (device
        batch round trips, queue wait excluded) instead of the serving
        plane's handler wall clock, and attach the device-plane telemetry
        recorder (queue waits, fill ratios, flush reasons, phase timings,
        slow-decision flight recorder)."""
        self._metrics = metrics
        self.recorder = DeviceStatsRecorder(metrics)
        for mb, ub in self._batcher_pairs():
            mb.metrics = metrics
            ub.metrics = metrics
            mb.recorder = self.recorder
            ub.recorder = self.recorder
        self.reports_datastore_latency = True

    async def check_and_update(
        self, counters: List[Counter], delta: int, load_counters: bool
    ) -> Authorization:
        if not counters:
            return Authorization.OK
        adm = self.admission
        if adm is not None and adm.use_failover():
            # Breaker open/half-open: exact host-oracle decision, no
            # batch slot, no device touch (deltas journal for the
            # recovery reconcile).
            return adm.failover_check_and_update(
                counters, delta, load_counters
            )
        batcher, _ub = self._batchers_for_loop()
        return await batcher.submit(counters, delta, load_counters)

    def set_limits_provider(self, provider) -> None:
        """Forwarded so the facade's registry reaches replicated inner
        storages (wire-key decode of gossiped counters)."""
        if hasattr(self.inner, "set_limits_provider"):
            self.inner.set_limits_provider(provider)

    async def is_within_limits(self, counter: Counter, delta: int) -> bool:
        adm = self.admission
        if adm is not None and adm.use_failover():
            return adm.failover_is_within_limits(counter, delta)
        return self.inner.is_within_limits(counter, delta)

    async def add_counter(self, limit: Limit) -> None:
        self.inner.add_counter(limit)

    async def update_counter(self, counter: Counter, delta: int) -> None:
        adm = self.admission
        if adm is not None and adm.use_failover():
            require_nonnegative_delta(delta)
            adm.failover_update_counter(counter, delta)
            return
        _mb, update_batcher = self._batchers_for_loop()
        await update_batcher.submit(counter, delta)

    def library_stats(self) -> dict:
        """Operational metrics for the /metrics library gauges,
        aggregated across serving shards."""
        flush_sizes: List[int] = []
        batcher_size = 0
        queue_depth = 0
        for mb, ub in self._batcher_pairs():
            shard_sizes, mb.flush_sizes = mb.flush_sizes, []
            flush_sizes.extend(shard_sizes)
            batcher_size += mb._pending_hits + len(ub._pending)
            queue_depth += len(mb._pending) + len(ub._pending)
        cache_size = 0
        table = getattr(self.inner, "_table", None)
        if table is not None:
            cache_size = len(table.qualified) + len(table.simple)
        else:  # sharded: per-shard tables + the psum global region
            for t in getattr(self.inner, "_tables", ()):
                cache_size += len(t.qualified) + len(t.simple)
            gtable = getattr(self.inner, "_gtable", None)
            if gtable is not None:
                cache_size += len(gtable.qualified) + len(gtable.simple)
        stats = {
            "batcher_size": batcher_size,
            "cache_size": cache_size,
            "flush_sizes": flush_sizes,
            "queue_depth": queue_depth,
        }
        launch_stats = getattr(self.inner, "launch_stats", None)
        if callable(launch_stats):
            # sharded storage: per-variant multi-chip launch tallies
            # (the sharded_launches metric family).
            stats.update(launch_stats())
        return stats

    def device_stats(self) -> dict:
        """Per-shard device table stats, delegated to the wrapped storage
        (single-chip, sharded and replicated all expose the same shape)."""
        inner_stats = getattr(self.inner, "device_stats", None)
        return inner_stats() if callable(inner_stats) else {"shards": []}

    async def get_counters(self, limits) -> set:
        return self.inner.get_counters(limits)

    async def delete_counters(self, limits) -> None:
        self.inner.delete_counters(limits)

    async def clear(self) -> None:
        self.inner.clear()

    async def close(self) -> None:
        cur = asyncio.get_running_loop()
        closed: set = set()
        for loop, (mb, ub) in list(self._loop_batchers.items()):
            if id(mb) in closed or loop is cur:
                continue  # current-loop / default pair closed below
            if not loop.is_closed() and loop.is_running():
                closed.add(id(mb))
                try:
                    asyncio.run_coroutine_threadsafe(
                        mb.close(), loop
                    ).result(timeout=10)
                    asyncio.run_coroutine_threadsafe(
                        ub.close(), loop
                    ).result(timeout=10)
                except Exception:
                    pass  # shard loop died mid-shutdown
        for mb, ub in self._batcher_pairs():
            if id(mb) in closed:
                continue
            # Current-loop shards, the default pair, and pairs whose loop
            # already died: close here (awaiting a dead loop's task is
            # guarded inside MicroBatcher.close by the task's own state).
            try:
                await mb.close()
                await ub.close()
            except Exception:
                pass
