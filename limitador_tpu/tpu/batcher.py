"""Async micro-batcher: many concurrent checks -> one fused kernel launch.

The serving plane (gRPC/HTTP handlers) awaits ``AsyncTpuStorage`` methods;
concurrent ``check_and_update`` calls are coalesced into a single device
batch. This is where p99 <= 2ms is won or lost (SURVEY.md §7.4): the batcher
flushes on (a) batch full, (b) the oldest request exceeding ``max_delay``,
mirroring the size|interval|priority triple of the reference's write-behind
Batcher (/root/reference/limitador/src/storage/redis/counters_cache.rs:183-238)
— except here the flush IS the decision, not an async reconciliation, so
admission stays exact.

Within a batch, requests keep their enqueue order and the kernel decides
admission exactly as if they were processed serially; all hit-building and
result-decoding semantics live in ``TpuStorage.check_many`` — the batcher
only owns the coalescing.
"""

from __future__ import annotations

import asyncio
from typing import List, Optional

from ..core.counter import Counter
from ..core.limit import Limit
from ..storage.base import AsyncCounterStorage, Authorization
from .storage import TpuStorage, _Request

__all__ = ["MicroBatcher", "AsyncTpuStorage"]


class MicroBatcher:
    def __init__(
        self,
        storage: TpuStorage,
        max_batch_hits: int = 8192,
        max_delay: float = 0.0005,
    ):
        self.storage = storage
        self.max_batch_hits = max_batch_hits
        self.max_delay = max_delay
        self._pending: List[tuple] = []  # (_Request, Future)
        self._pending_hits = 0
        self._wakeup: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._closed = False

    def _ensure_started(self) -> None:
        if self._task is None or self._task.done():
            self._wakeup = asyncio.Event()
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def submit(
        self, counters: List[Counter], delta: int, load: bool
    ) -> Authorization:
        """Enqueue one request; resolves when its batch has been decided."""
        self._ensure_started()
        future = asyncio.get_running_loop().create_future()
        request = _Request(counters, delta, load)
        self._pending.append((request, future))
        self._pending_hits += len(request.ordered)
        self._wakeup.set()
        return await future

    async def _run(self) -> None:
        while not self._closed:
            while not self._pending:
                self._wakeup.clear()
                if self._closed:
                    return
                try:
                    await asyncio.wait_for(self._wakeup.wait(), timeout=1.0)
                except asyncio.TimeoutError:
                    if self._closed:
                        return
            if self._pending_hits < self.max_batch_hits:
                # Linger briefly to let concurrent requests coalesce.
                await asyncio.sleep(self.max_delay)
            batch = self._pending
            self._pending = []
            self._pending_hits = 0
            try:
                auths = self.storage.check_many([r for r, _f in batch])
                for (_r, future), auth in zip(batch, auths):
                    if not future.done():
                        future.set_result(auth)
            except Exception as exc:  # propagate to every waiter
                for _r, future in batch:
                    if not future.done():
                        future.set_exception(exc)

    async def close(self) -> None:
        self._closed = True
        if self._wakeup is not None:
            self._wakeup.set()
        if self._task is not None:
            try:
                await self._task
            except asyncio.CancelledError:
                pass


class AsyncTpuStorage(AsyncCounterStorage):
    """AsyncCounterStorage over TpuStorage + MicroBatcher: the hot
    check_and_update path batches; admin operations delegate inline."""

    def __init__(
        self,
        storage: Optional[TpuStorage] = None,
        max_batch_hits: int = 8192,
        max_delay: float = 0.0005,
        **kwargs,
    ):
        self.inner = storage or TpuStorage(**kwargs)
        self.batcher = MicroBatcher(self.inner, max_batch_hits, max_delay)

    async def check_and_update(
        self, counters: List[Counter], delta: int, load_counters: bool
    ) -> Authorization:
        if not counters:
            return Authorization.OK
        return await self.batcher.submit(counters, delta, load_counters)

    def set_limits_provider(self, provider) -> None:
        """Forwarded so the facade's registry reaches replicated inner
        storages (wire-key decode of gossiped counters)."""
        if hasattr(self.inner, "set_limits_provider"):
            self.inner.set_limits_provider(provider)

    async def is_within_limits(self, counter: Counter, delta: int) -> bool:
        return self.inner.is_within_limits(counter, delta)

    async def add_counter(self, limit: Limit) -> None:
        self.inner.add_counter(limit)

    async def update_counter(self, counter: Counter, delta: int) -> None:
        self.inner.update_counter(counter, delta)

    async def get_counters(self, limits) -> set:
        return self.inner.get_counters(limits)

    async def delete_counters(self, limits) -> None:
        self.inner.delete_counters(limits)

    async def clear(self) -> None:
        self.inner.clear()

    async def close(self) -> None:
        await self.batcher.close()
