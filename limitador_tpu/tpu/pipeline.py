"""Compiled TPU pipeline: descriptor batches -> masks -> slots -> kernel.

The fully TPU-native request path (SURVEY.md §7.3): instead of interpreting
CEL per request before storage (lib.rs:507-522), raw requests
(namespace, descriptor map, delta) queue into the micro-batcher; at flush
the whole batch evaluates through the vectorized limit compiler
(tpu/compiler.py) — one columnar pass per namespace — and the resulting
counters go through the same exact device kernel as the per-request path.

``CompiledTpuLimiter`` is a drop-in ``AsyncRateLimiter``: same public API,
same semantics (the compiler is equivalence-tested against the CEL
interpreter), same storage. Namespace compilers rebuild lazily whenever
that namespace's limits change.
"""

from __future__ import annotations

import asyncio
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple, Union

from ..core.cel import Context
from ..core.counter import Counter
from ..core.limiter import AsyncRateLimiter, CheckResult
from ..core.limit import Limit, Namespace
from ..observability.device_plane import current_request_id
from ..observability.tracing import datastore_span, device_batch_span
from .batcher import AsyncTpuStorage, _latency_hists, _timed_call
from .compiler import NamespaceCompiler

__all__ = ["CompiledTpuLimiter"]


class _RawPending:
    __slots__ = (
        "namespace", "values", "delta", "load", "future", "t_enq", "rid",
    )

    def __init__(self, namespace, values, delta, load, future,
                 t_enq=0.0, rid=None):
        self.namespace = namespace
        self.values = values
        self.delta = delta
        self.load = load
        self.future = future
        self.t_enq = t_enq
        self.rid = rid


def _values_of(
    ctx_or_values: Union[Context, Dict[str, str]]
) -> Optional[Dict[str, str]]:
    """Descriptor map when the context has exactly the single-descriptor
    shape the compiler handles; None routes the request to the exact
    per-request path (multi-descriptor requests, root-bound library
    contexts, ...)."""
    if isinstance(ctx_or_values, dict):
        return ctx_or_values
    bindings = ctx_or_values._bindings
    descriptors = bindings.get("descriptors")
    if (
        descriptors is not None
        and len(descriptors) == 1
        and len(bindings) == 1
    ):
        return descriptors[0]
    return None


class CompiledTpuLimiter(AsyncRateLimiter):
    """AsyncRateLimiter whose hot path batch-compiles limit evaluation.

    Restriction (checked at evaluation): compiled evaluation binds the
    request's descriptor map as ``descriptors[0]`` — the same shape the
    RLS/HTTP serving plane uses. Exotic contexts still work through the
    inherited per-request path.
    """

    reports_datastore_latency = False

    def __init__(self, storage: Optional[AsyncTpuStorage] = None, **kwargs):
        super().__init__(storage or AsyncTpuStorage(**kwargs))
        self._metrics = None
        # Device-plane telemetry sink, shared with the wrapped storage's
        # micro-batcher (one batch-id sequence, one flight recorder per
        # process). None until set_metrics — detached costs nothing.
        self.recorder = None
        self._retired_vec_evals = 0
        self._retired_fb_evals = 0
        self._tpu: AsyncTpuStorage = self.storage.counters
        self._compilers: Dict[Namespace, NamespaceCompiler] = {}
        self._rev: Dict[Namespace, List[str]] = {}
        self._pending: List[_RawPending] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # seq -> the _RawPendings of a dispatched-but-uncollected batch,
        # so an admission-plane breaker trip can fail them off the dead
        # plane (mirrors MicroBatcher._inflight_batches).
        self._inflight_pendings: Dict[int, list] = {}
        self._batch_seq = 0
        self._flush_task: Optional[asyncio.Task] = None
        self.max_delay = self._tpu.batcher.max_delay
        self.max_batch = 4096
        #: dispatched-but-uncollected batches (the MicroBatcher pattern):
        #: batch N+1's evaluate + kernel launch overlaps batch N's
        #: device round trip.
        self.max_inflight = 2
        self._dispatch_pool = ThreadPoolExecutor(
            1, thread_name_prefix="compiled-dispatch"
        )
        self._collect_pool = ThreadPoolExecutor(
            self.max_inflight, thread_name_prefix="compiled-collect"
        )
        self._inflight: set = set()
        self._inflight_sem: Optional[asyncio.Semaphore] = None

    # -- compiler cache invalidation ----------------------------------------

    def _invalidate(self, namespace: Namespace) -> None:
        self._retire_compiler(self._compilers.pop(namespace, None))

    def add_limit(self, limit: Limit) -> bool:
        self._invalidate(limit.namespace)
        return super().add_limit(limit)

    def update_limit(self, limit: Limit) -> bool:
        self._invalidate(limit.namespace)
        return super().update_limit(limit)

    async def delete_limit(self, limit: Limit) -> None:
        self._invalidate(limit.namespace)
        await super().delete_limit(limit)

    async def delete_limits(self, namespace) -> None:
        self._invalidate(Namespace.of(namespace))
        await super().delete_limits(namespace)

    async def configure_with(self, limits) -> None:
        for compiler in self._compilers.values():
            self._retire_compiler(compiler)
        self._compilers.clear()
        await super().configure_with(limits)

    def set_metrics(self, metrics) -> None:
        """Report device-batch datastore latency + compiler eval counters
        through the server's metrics layer."""
        self._metrics = metrics
        self.reports_datastore_latency = True
        if hasattr(self._tpu, "set_metrics"):
            # Requests with exotic context shapes fall back to the standard
            # micro-batcher, which then reports its own device time.
            self._tpu.set_metrics(metrics)
        self.recorder = getattr(self._tpu, "recorder", None)

    def _retire_compiler(self, compiler) -> None:
        if compiler is not None:
            self._retired_vec_evals += compiler.vectorized_evals
            self._retired_fb_evals += compiler.fallback_evals

    def library_stats(self) -> dict:
        stats = (
            self._tpu.library_stats()
            if hasattr(self._tpu, "library_stats")
            else {}
        )
        vec, fb = self._retired_vec_evals, self._retired_fb_evals
        for compiler in self._compilers.values():
            vec += compiler.vectorized_evals
            fb += compiler.fallback_evals
        stats["cel_vectorized_evals"] = vec
        stats["cel_fallback_evals"] = fb
        stats["queue_depth"] = stats.get("queue_depth", 0) + len(self._pending)
        return stats

    def device_stats(self) -> dict:
        inner_stats = getattr(self._tpu, "device_stats", None)
        return inner_stats() if callable(inner_stats) else {"shards": []}

    def _compiler_for(self, namespace: Namespace) -> NamespaceCompiler:
        compiler = self._compilers.get(namespace)
        if compiler is None:
            compiler = NamespaceCompiler(self.get_limits(namespace))
            self._compilers[namespace] = compiler
        return compiler

    # -- the batched hot path -------------------------------------------------

    async def check_rate_limited_and_update(
        self,
        namespace,
        ctx: Union[Context, Dict[str, str]],
        delta: int,
        load_counters: bool = False,
    ) -> CheckResult:
        namespace = Namespace.of(namespace)
        adm = getattr(self._tpu, "admission", None)
        if adm is not None and adm.use_failover():
            # Device-plane breaker open: the inherited exact path routes
            # through the storage, whose failover branch decides against
            # the host oracle — no batch slot, no device touch. The
            # compiled surface also accepts bare descriptor maps; the
            # exact path needs a real Context.
            if isinstance(ctx, dict):
                values, ctx = ctx, Context()
                ctx.list_binding("descriptors", [values])
            return await super().check_rate_limited_and_update(
                namespace, ctx, delta, load_counters
            )
        values = _values_of(ctx)
        if values is None:
            # Context shape the compiler doesn't cover: exact inherited path.
            return await super().check_rate_limited_and_update(
                namespace, ctx, delta, load_counters
            )
        self._loop = asyncio.get_running_loop()
        future = asyncio.get_running_loop().create_future()
        rid = current_request_id() if self.recorder is not None else None
        self._pending.append(
            _RawPending(
                namespace, values, delta, load_counters, future,
                time.perf_counter(), rid,
            )
        )
        if self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.get_running_loop().create_task(
                self._flush_soon()
            )
        # The wait for the batched device decision IS this request's
        # datastore time: a record span here rolls it up under the
        # should_rate_limit aggregate (queue/linger counts as idle, the
        # reference's semantics for awaited storage futures).
        with datastore_span("check_and_update"):
            if len(self._pending) >= self.max_batch:
                await self._flush()
            return await future

    async def _flush_soon(self) -> None:
        await asyncio.sleep(self.max_delay)
        await self._flush()
        # Requests that arrived while the flush was busy on the device must
        # not wait for the NEXT submission to schedule a timer — re-arm
        # unconditionally (this coroutine IS the current _flush_task, so a
        # done() check here would always see itself as running).
        if self._pending:
            self._flush_task = asyncio.get_running_loop().create_task(
                self._flush_soon()
            )

    async def _flush(self, reason: Optional[str] = None) -> None:
        batch, self._pending = self._pending, []
        if not batch:
            return
        loop = asyncio.get_running_loop()
        if self._inflight_sem is None:
            self._inflight_sem = asyncio.Semaphore(self.max_inflight)
        rec = self.recorder
        t_flush = time.perf_counter()
        batch_id = 0
        if rec is not None:
            batch_id = rec.next_batch_id()
            rec.record_flush(
                reason or (
                    "size" if len(batch) >= self.max_batch else "deadline"
                ),
                len(batch) / self.max_batch,
                [t_flush - p.t_enq for p in batch],
            )
        live: List[Tuple[_RawPending, List[Counter]]] = []
        try:
            # Columnar evaluation stays ON the loop thread: the compiler
            # cache and the limits registry are only ever touched here,
            # so a concurrent limits reload cannot hand a batch a
            # half-rebuilt plan. Only the kernel launch (dispatch thread,
            # launch order = device program order) and the device
            # transfer (collect threads) go off-loop — that's where the
            # round-trip time lives.
            from .storage import _Request

            requests = self._evaluate_batch(batch)
            for p, counters in requests:
                if not counters:
                    if not p.future.done():
                        p.future.set_result(CheckResult(False, [], None))
                else:
                    live.append((p, counters))
            if not live:
                return
            reqs = [_Request(c, p.delta, p.load) for p, c in live]
            t_eval = time.perf_counter()
            await self._inflight_sem.acquire()
        except BaseException as exc:
            # Nothing may escape silently: an exception (INCLUDING a
            # cancellation of the submitter awaiting this flush) lost here
            # would strand every other submitter of this batch.
            _fail_futures(batch, exc)
            raise
        t_submit = time.perf_counter()
        adm = getattr(self._tpu, "admission", None)
        token = adm.breaker.batch_started() if adm is not None else 0
        self._batch_seq += 1
        seq = self._batch_seq
        self._inflight_pendings[seq] = [p for p, _c in live]
        try:
            handle, t_begin, t_launch = await loop.run_in_executor(
                self._dispatch_pool, _timed_call,
                self._tpu.inner.begin_check_many, reqs,
            )
        except BaseException as exc:
            self._inflight_sem.release()
            self._inflight_pendings.pop(seq, None)
            if adm is not None:
                adm.breaker.batch_finished(token, exc)
            _fail_futures([p for p, _c in live], exc)
            if not isinstance(exc, Exception):
                raise
            return
        # host_stage folds the on-loop columnar evaluation in with the
        # kernel launch: both are host work this batch paid before the
        # device round trip. The inflight-semaphore wait (t_eval ->
        # t_submit) is backpressure queueing, not host work — excluded,
        # matching the native pipeline's post-acquire t_submit.
        phases = {
            "dispatch": t_begin - t_submit,
            "host_stage": (t_eval - t_flush) + (t_launch - t_begin),
        }
        t0 = time.perf_counter()
        task = loop.run_in_executor(
            self._collect_pool, self._collect_batch, handle, live, t0,
            batch_id, t_flush, phases,
        )
        self._inflight.add(task)

        def _collected(t):
            self._inflight.discard(t)
            self._inflight_pendings.pop(seq, None)
            self._inflight_sem.release()
            exc = t.exception()
            if adm is not None:
                adm.breaker.batch_finished(token, exc)
            if exc is not None:
                _fail_futures([p for p, _c in live], exc)

        task.add_done_callback(_collected)

    def _collect_batch(
        self, handle, live, t0: float, batch_id: int = 0,
        t_flush: float = 0.0, phases: Optional[dict] = None,
    ) -> None:
        """Collect-thread phase: device transfer, decode, resolve every
        future in one loop callback per loop."""
        with device_batch_span(batch_id, len(live)) as span_phases:
            auths, t_fin, t_done = _timed_call(
                self._tpu.inner.finish_check_many, handle
            )
            if self._metrics is not None:
                dt = time.perf_counter() - t0
                for hist in _latency_hists(self._metrics):
                    for _ in live:
                        hist.observe(dt)
            by_loop: Dict[object, list] = {}
            for (p, counters), auth in zip(live, auths):
                loaded = counters if p.load else []
                result = CheckResult(auth.limited, loaded, auth.limit_name)
                by_loop.setdefault(p.future.get_loop(), []).append(
                    (p.future, result)
                )
            for floop, pairs in by_loop.items():
                floop.call_soon_threadsafe(_settle_results, pairs)
            rec = self.recorder
            if phases is None:
                return
            phases["device_sync"] = t_done - t_fin
            phases["unpack"] = time.perf_counter() - t_done
            span_phases(phases)
            if rec is None:
                return
            rec.record_batch(
                ((p.t_enq, p.rid, p.namespace) for p, _counters in live),
                batch_id, t_flush, phases,
            )

    def _evaluate_batch(
        self, batch: List[_RawPending]
    ) -> List[Tuple[_RawPending, List[Counter]]]:
        # Group by namespace; one columnar evaluation each.
        by_ns: Dict[Namespace, List[int]] = {}
        for i, p in enumerate(batch):
            by_ns.setdefault(p.namespace, []).append(i)

        requests: List[Tuple[_RawPending, List[Counter]]] = []
        src_cache: Dict[Limit, List[str]] = {}
        for namespace, idxs in by_ns.items():
            compiler = self._compiler_for(namespace)
            evaluated = compiler.evaluate([batch[i].values for i in idxs])
            strings = compiler.interner.strings
            for i, hits in zip(idxs, evaluated):
                counters = []
                for limit, tokens in hits:
                    var_sources = src_cache.get(limit)
                    if var_sources is None:
                        # limit.variables is already source-sorted
                        var_sources = [v.source for v in limit.variables]
                        src_cache[limit] = var_sources
                    set_vars = {
                        src: strings[tok]
                        for src, tok in zip(var_sources, tokens)
                    }
                    counters.append(Counter(limit, set_vars))
                requests.append((batch[i], counters))
        return requests

    def fail_over_queued(self, decider, exc) -> None:
        """Admission-plane breaker trip: decide every queued raw request
        host-side through ``decider(counters, delta, load) ->
        Authorization`` and fail dispatched-but-uncollected batches with
        ``exc`` (their kernel may already have run). Thread-safe — the
        trip listener can fire from a collect thread; the drain runs on
        the serving loop, where the compiler cache and limits registry
        are safe to touch (the ``_flush`` discipline)."""
        loop = self._loop
        if loop is None or loop.is_closed():
            return

        def _drain():
            batch, self._pending = self._pending, []
            if batch:
                try:
                    evaluated = self._evaluate_batch(batch)
                except Exception as eexc:
                    _fail_futures(batch, eexc)
                    evaluated = []
                for p, counters in evaluated:
                    if p.future.done():
                        continue
                    try:
                        if not counters:
                            p.future.set_result(CheckResult(False, [], None))
                        else:
                            auth = decider(counters, p.delta, p.load)
                            p.future.set_result(CheckResult(
                                auth.limited,
                                counters if p.load else [],
                                auth.limit_name,
                            ))
                    except Exception as dexc:
                        p.future.set_exception(dexc)
            for pendings in list(self._inflight_pendings.values()):
                _fail_futures(pendings, exc)

        loop.call_soon_threadsafe(_drain)

    async def close(self) -> None:
        """Drain in-flight collects and release the worker pools."""
        await self._flush("shutdown")
        if self._inflight:
            await asyncio.gather(*self._inflight, return_exceptions=True)
        self._dispatch_pool.shutdown(wait=False)
        self._collect_pool.shutdown(wait=False)


def _settle_results(pairs) -> None:
    for future, result in pairs:
        if not future.done():
            future.set_result(result)


def _fail_futures(pendings, exc) -> None:
    """Fail every unresolved pending, routed through each future's own
    loop (callers may run on a different loop's thread or a pool
    thread; set_exception is only safe from the owning loop)."""
    by_loop: Dict[object, list] = {}
    for p in pendings:
        future = p.future
        if not future.done():
            by_loop.setdefault(future.get_loop(), []).append(future)

    for floop, futures in by_loop.items():
        def _do(futures=futures):
            for future in futures:
                if not future.done():
                    future.set_exception(exc)

        floop.call_soon_threadsafe(_do)
