"""Compiled TPU pipeline: descriptor batches -> masks -> slots -> kernel.

The fully TPU-native request path (SURVEY.md §7.3): instead of interpreting
CEL per request before storage (lib.rs:507-522), raw requests
(namespace, descriptor map, delta) queue into the micro-batcher; at flush
the whole batch evaluates through the vectorized limit compiler
(tpu/compiler.py) — one columnar pass per namespace — and the resulting
counters go through the same exact device kernel as the per-request path.

``CompiledTpuLimiter`` is a drop-in ``AsyncRateLimiter``: same public API,
same semantics (the compiler is equivalence-tested against the CEL
interpreter), same storage. Namespace compilers rebuild lazily whenever
that namespace's limits change.

Two serving-path additions close the served/engine gap (ISSUE 3):

- **Counter-plan cache**: repeat (namespace, descriptor-values)
  identities skip CEL evaluation and Counter construction entirely —
  the resolved Counter list is memoized under a limits epoch that every
  add/update/delete/reload bumps (qualified-counter identity caching on
  the gRPC path).
- **Per-loop serving shards**: the pending queue, flush task and
  in-flight window are sharded per event loop, so N serving loops
  (threads) feed the one device lane concurrently; ``submit_check`` is
  the plain-function fast lane returning the decision future without a
  per-request coroutine.
"""

from __future__ import annotations

import asyncio
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple, Union

from ..core.cel import Context
from ..core.counter import Counter
from ..core.limiter import AsyncRateLimiter, CheckResult
from ..core.limit import Limit, Namespace
from ..observability.device_plane import current_request_id
from ..observability.tracing import datastore_span, device_batch_span
from .batcher import (
    AsyncTpuStorage,
    ChunkPlanner,
    _latency_hists,
    _timed_call,
    chunk_queue_wait,
)
from .compiler import NamespaceCompiler
from .plan_cache import CounterPlanCache

__all__ = ["CompiledTpuLimiter"]


class _RawPending:
    __slots__ = (
        "namespace", "values", "delta", "load", "future", "t_enq", "rid",
    )

    def __init__(self, namespace, values, delta, load, future,
                 t_enq=0.0, rid=None):
        self.namespace = namespace
        self.values = values
        self.delta = delta
        self.load = load
        self.future = future
        self.t_enq = t_enq
        self.rid = rid


class _LoopShard:
    """Per-event-loop serving state (pending queue + flush machinery).
    Each serving loop owns one; the compiler cache, limits registry and
    device lane behind them are shared."""

    __slots__ = (
        "loop", "pending", "flush_task", "sem", "inflight",
        "inflight_pendings", "batch_seq",
    )

    def __init__(self, loop, max_inflight: int):
        self.loop = loop
        self.pending: List[_RawPending] = []
        self.flush_task: Optional[asyncio.Task] = None
        self.sem = asyncio.Semaphore(max_inflight)
        self.inflight: set = set()
        # seq -> the _RawPendings of a dispatched-but-uncollected batch,
        # so an admission-plane breaker trip can fail them off the dead
        # plane (mirrors MicroBatcher._inflight_batches).
        self.inflight_pendings: Dict[int, list] = {}
        self.batch_seq = 0


def _values_of(
    ctx_or_values: Union[Context, Dict[str, str]]
) -> Optional[Dict[str, str]]:
    """Descriptor map when the context has exactly the single-descriptor
    shape the compiler handles; None routes the request to the exact
    per-request path (multi-descriptor requests, root-bound library
    contexts, ...)."""
    if isinstance(ctx_or_values, dict):
        return ctx_or_values
    bindings = ctx_or_values._bindings
    descriptors = bindings.get("descriptors")
    if (
        descriptors is not None
        and len(descriptors) == 1
        and len(bindings) == 1
    ):
        return descriptors[0]
    return None


class CompiledTpuLimiter(AsyncRateLimiter):
    """AsyncRateLimiter whose hot path batch-compiles limit evaluation.

    Restriction (checked at evaluation): compiled evaluation binds the
    request's descriptor map as ``descriptors[0]`` — the same shape the
    RLS/HTTP serving plane uses. Exotic contexts still work through the
    inherited per-request path.
    """

    reports_datastore_latency = False

    def __init__(
        self,
        storage: Optional[AsyncTpuStorage] = None,
        plan_cache_size: int = 1 << 16,
        dispatch_chunk: Optional[int] = None,
        **kwargs,
    ):
        super().__init__(storage or AsyncTpuStorage(**kwargs))
        # Pipelined sub-batch dispatch (batcher.py module docstring):
        # None = auto-tuned from the queue-wait signal, 0 = monolithic.
        self.chunk_planner = ChunkPlanner(dispatch_chunk)
        self._metrics = None
        # Device-plane telemetry sink, shared with the wrapped storage's
        # micro-batcher (one batch-id sequence, one flight recorder per
        # process). None until set_metrics — detached costs nothing.
        self.recorder = None
        self._retired_vec_evals = 0
        self._retired_fb_evals = 0
        self._tpu: AsyncTpuStorage = self.storage.counters
        self._compilers: Dict[Namespace, NamespaceCompiler] = {}
        self._rev: Dict[Namespace, List[str]] = {}
        # Epoch-guarded (namespace, values) -> Counter-list memo; every
        # limits change bumps the epoch, orphaning all entries.
        self.counters_cache: Optional[CounterPlanCache] = (
            CounterPlanCache(plan_cache_size) if plan_cache_size > 0
            else None
        )
        self._shards: Dict[object, _LoopShard] = {}
        self._shards_lock = threading.Lock()
        # Serializes compiler-cache access + columnar evaluation across
        # serving shards: NamespaceCompiler's interner is a
        # check-then-act (token = len(strings)) that two shard loops
        # evaluating concurrently could double-assign, aliasing two
        # descriptor values onto one token — i.e. one user's traffic
        # debiting another's counter. Only cache MISSES pay this lock.
        self._eval_lock = threading.Lock()
        self.max_delay = self._tpu.batcher.max_delay
        self.max_batch = 4096
        #: dispatched-but-uncollected batches PER SHARD (the MicroBatcher
        #: pattern): batch N+1's evaluate + kernel launch overlaps batch
        #: N's device round trip.
        self.max_inflight = 2
        self._dispatch_pool = ThreadPoolExecutor(
            1, thread_name_prefix="compiled-dispatch"
        )
        self._collect_pool = ThreadPoolExecutor(
            max(self.max_inflight, 2), thread_name_prefix="compiled-collect"
        )

    @property
    def _pending(self):
        """Aggregate pending across serving shards (stats/debug only)."""
        out: list = []
        for shard in list(self._shards.values()):
            out.extend(shard.pending)
        return out

    # -- compiler cache invalidation ----------------------------------------
    #
    # Ordering + locking contract (serving shards): invalidation runs
    # AFTER the registry mutation and takes _eval_lock. A shard's miss
    # evaluation holds _eval_lock from reading get_limits to installing
    # the built compiler, so by the time the invalidate acquires the
    # lock, any compiler built from the pre-mutation registry is already
    # installed — and gets popped here; any compiler built after the pop
    # reads the post-mutation registry. (Invalidate-before-mutation
    # would leave a window where a shard installs a stale compiler
    # after the pop, serving retired limits indefinitely.)

    def _invalidate(self, namespace: Namespace) -> None:
        with self._eval_lock:
            self._retire_compiler(self._compilers.pop(namespace, None))
            if self.counters_cache is not None:
                self.counters_cache.bump_epoch()

    def add_limit(self, limit: Limit) -> bool:
        added = super().add_limit(limit)
        self._invalidate(limit.namespace)
        return added

    def update_limit(self, limit: Limit) -> bool:
        updated = super().update_limit(limit)
        self._invalidate(limit.namespace)
        return updated

    async def delete_limit(self, limit: Limit) -> None:
        await super().delete_limit(limit)
        self._invalidate(limit.namespace)

    async def delete_limits(self, namespace) -> None:
        await super().delete_limits(namespace)
        self._invalidate(Namespace.of(namespace))

    async def configure_with(self, limits) -> None:
        await super().configure_with(limits)
        with self._eval_lock:
            for compiler in self._compilers.values():
                self._retire_compiler(compiler)
            self._compilers.clear()
            if self.counters_cache is not None:
                self.counters_cache.bump_epoch()

    def set_metrics(self, metrics) -> None:
        """Report device-batch datastore latency + compiler eval counters
        through the server's metrics layer."""
        self._metrics = metrics
        self.reports_datastore_latency = True
        if hasattr(self._tpu, "set_metrics"):
            # Requests with exotic context shapes fall back to the standard
            # micro-batcher, which then reports its own device time.
            self._tpu.set_metrics(metrics)
        self.recorder = getattr(self._tpu, "recorder", None)

    def _retire_compiler(self, compiler) -> None:
        if compiler is not None:
            self._retired_vec_evals += compiler.vectorized_evals
            self._retired_fb_evals += compiler.fallback_evals

    def plan_cache_stats(self) -> dict:
        return (
            self.counters_cache.stats()
            if self.counters_cache is not None else {}
        )

    def library_stats(self) -> dict:
        stats = (
            self._tpu.library_stats()
            if hasattr(self._tpu, "library_stats")
            else {}
        )
        vec, fb = self._retired_vec_evals, self._retired_fb_evals
        for compiler in self._compilers.values():
            vec += compiler.vectorized_evals
            fb += compiler.fallback_evals
        stats["cel_vectorized_evals"] = vec
        stats["cel_fallback_evals"] = fb
        stats["queue_depth"] = stats.get("queue_depth", 0) + len(self._pending)
        stats.update(self.plan_cache_stats())
        return stats

    def device_stats(self) -> dict:
        inner_stats = getattr(self._tpu, "device_stats", None)
        return inner_stats() if callable(inner_stats) else {"shards": []}

    def _compiler_for(self, namespace: Namespace) -> NamespaceCompiler:
        compiler = self._compilers.get(namespace)
        if compiler is None:
            compiler = NamespaceCompiler(self.get_limits(namespace))
            self._compilers[namespace] = compiler
        return compiler

    # -- the batched hot path -------------------------------------------------

    def _shard_for(self, loop) -> _LoopShard:
        shard = self._shards.get(loop)
        if shard is not None:
            return shard
        with self._shards_lock:
            shard = self._shards.get(loop)
            if shard is None:
                # Prune shards whose loop died so loop churn cannot
                # leak shard structs for the limiter's lifetime.
                for dead in [l for l in self._shards if l.is_closed()]:
                    del self._shards[dead]
                shard = _LoopShard(loop, self.max_inflight)
                self._shards[loop] = shard
            return shard

    def submit_check(
        self,
        namespace: Namespace,
        values: Dict[str, str],
        delta: int,
        load_counters: bool = False,
    ) -> "asyncio.Future":
        """Sync fast lane: enqueue one compiled-shape check on the
        calling loop's shard; returns the CheckResult future. One future
        + one append per request — no per-request coroutine."""
        loop = asyncio.get_running_loop()
        shard = self._shards.get(loop)
        if shard is None:
            shard = self._shard_for(loop)
        future = loop.create_future()
        # Timestamp unconditionally (a recorder attached between enqueue
        # and flush would otherwise read t_enq=0.0 as a huge queue
        # wait); only the request-id capture is recorder-gated.
        shard.pending.append(_RawPending(
            namespace, values, delta, load_counters, future,
            time.perf_counter(),
            current_request_id() if self.recorder is not None else None,
        ))
        task = shard.flush_task
        if task is None or task.done():
            shard.flush_task = loop.create_task(self._flush_soon(shard))
        if len(shard.pending) == self.max_batch:
            # == not >=: one size-flush per threshold crossing, not one
            # per submit past it (bursts enqueue before the loop runs).
            loop.create_task(self._flush(shard, "size"))
        return future

    async def check_rate_limited_and_update(
        self,
        namespace,
        ctx: Union[Context, Dict[str, str]],
        delta: int,
        load_counters: bool = False,
        counters=None,
    ) -> CheckResult:
        namespace = Namespace.of(namespace)
        adm = getattr(self._tpu, "admission", None)
        if adm is not None and adm.use_failover():
            # Device-plane breaker open: the inherited exact path routes
            # through the storage, whose failover branch decides against
            # the host oracle — no batch slot, no device touch. The
            # compiled surface also accepts bare descriptor maps; the
            # exact path needs a real Context.
            if isinstance(ctx, dict):
                values, ctx = ctx, Context()
                ctx.list_binding("descriptors", [values])
                counters = None  # matched against the rebuilt context
            return await super().check_rate_limited_and_update(
                namespace, ctx, delta, load_counters, counters=counters
            )
        values = _values_of(ctx)
        if values is None:
            # Context shape the compiler doesn't cover: exact inherited path.
            return await super().check_rate_limited_and_update(
                namespace, ctx, delta, load_counters, counters=counters
            )
        # The batched fast lane below matches columnar per FLUSH (one
        # vectorized evaluation for the whole batch) — a per-request
        # ``counters`` precompute has no second matching to save there,
        # so it is deliberately ignored on this branch (ISSUE 13).
        # The wait for the batched device decision IS this request's
        # datastore time: a record span here rolls it up under the
        # should_rate_limit aggregate (queue/linger counts as idle, the
        # reference's semantics for awaited storage futures).
        with datastore_span("check_and_update"):
            return await self.submit_check(
                namespace, values, delta, load_counters
            )

    async def _flush_soon(self, shard: _LoopShard) -> None:
        await asyncio.sleep(self.max_delay)
        await self._flush(shard)
        # Requests that arrived while the flush was busy on the device must
        # not wait for the NEXT submission to schedule a timer — re-arm
        # unconditionally (this coroutine IS the current flush_task, so a
        # done() check here would always see itself as running).
        if shard.pending:
            shard.flush_task = asyncio.get_running_loop().create_task(
                self._flush_soon(shard)
            )

    async def _flush(
        self, shard: _LoopShard, reason: Optional[str] = None
    ) -> None:
        batch, shard.pending = shard.pending, []
        if not batch:
            return
        loop = asyncio.get_running_loop()
        rec = self.recorder
        t_flush = time.perf_counter()
        batch_id = 0
        if rec is not None:
            batch_id = rec.next_batch_id()
            try:
                rec.record_flush(
                    reason or (
                        "size" if len(batch) >= self.max_batch
                        else "deadline"
                    ),
                    len(batch) / self.max_batch,
                    [t_flush - p.t_enq for p in batch],
                )
            except Exception:
                pass  # telemetry must never strand a batch's futures
        live: List[Tuple[_RawPending, List[Counter]]] = []
        try:
            # Columnar evaluation stays ON the serving loop thread: the
            # counters cache absorbs repeat identities; misses touch the
            # compiler cache and limits registry, whose mutation sites
            # (limits reload) run on the main loop — a concurrent reload
            # races a shard's batch only into deciding with the
            # just-retired limits, the same window a batch flushed
            # moments earlier had. Only the kernel launch (dispatch
            # thread, launch order = device program order) and the device
            # transfer (collect threads) go off-loop — that's where the
            # round-trip time lives.
            from .storage import _Request

            requests = self._evaluate_batch(batch)
            for p, counters in requests:
                if not counters:
                    if not p.future.done():
                        p.future.set_result(CheckResult(False, [], None))
                else:
                    live.append((p, counters))
            if not live:
                return
            reqs = [_Request(c, p.delta, p.load) for p, c in live]
            t_eval = time.perf_counter()
        except BaseException as exc:
            # Nothing may escape silently: an exception (INCLUDING a
            # cancellation of the submitter awaiting this flush) lost here
            # would strand every other submitter of this batch.
            _fail_futures(batch, exc)
            raise
        adm = getattr(self._tpu, "admission", None)
        # Chunked pipelined dispatch (batcher.py ChunkPlanner): the flush
        # splits into sub-batches riding the shard's inflight window, so
        # a request's device round trip is its chunk's, not the flush's.
        ranges = self.chunk_planner.split(
            [len(c) for _p, c in live],
            chunk_queue_wait(adm, batch[0].t_enq, t_flush),
        )
        if rec is not None:
            rec.record_chunks([
                sum(len(c) for _p, c in live[lo:hi]) for lo, hi in ranges
            ])
        # Every chunk registers as in-flight BEFORE any await, so a
        # breaker trip can fail chunks still waiting on the window (they
        # left shard.pending at the top of this flush).
        chunk_seqs = []
        for lo, hi in ranges:
            shard.batch_seq += 1
            shard.inflight_pendings[shard.batch_seq] = [
                p for p, _c in live[lo:hi]
            ]
            chunk_seqs.append(shard.batch_seq)

        def _drop_rest(idx, exc):
            """Fail (and deregister) chunk idx onward — nothing may be
            left silently stranded when this coroutine unwinds."""
            for (l2, h2), s2 in zip(ranges[idx:], chunk_seqs[idx:]):
                shard.inflight_pendings.pop(s2, None)
                _fail_futures([p for p, _c in live[l2:h2]], exc)

        failed = None
        for ci, ((lo, hi), seq) in enumerate(zip(ranges, chunk_seqs)):
            sub_live = live[lo:hi]
            if failed is not None:
                # begin failures are plane-wide: the rest of the flush
                # fails the way a monolithic dispatch would have.
                shard.inflight_pendings.pop(seq, None)
                _fail_futures([p for p, _c in sub_live], failed)
                continue
            try:
                await shard.sem.acquire()
            except BaseException as exc:
                _drop_rest(ci, exc)
                raise
            t_submit = time.perf_counter()
            token = adm.breaker.batch_started() if adm is not None else 0
            try:
                handle, t_begin, t_launch = await loop.run_in_executor(
                    self._dispatch_pool, _timed_call,
                    self._tpu.inner.begin_check_many, reqs[lo:hi],
                )
            except BaseException as exc:
                shard.sem.release()
                if adm is not None:
                    adm.breaker.batch_finished(token, exc)
                if not isinstance(exc, Exception):
                    _drop_rest(ci, exc)
                    raise
                shard.inflight_pendings.pop(seq, None)
                _fail_futures([p for p, _c in sub_live], exc)
                failed = exc
                continue
            # host_stage folds the on-loop columnar evaluation in with
            # the kernel launch: both are host work this batch paid
            # before the device round trip (the evaluation share is
            # attributed to the first chunk — it ran once for the whole
            # flush). The inflight-semaphore wait (t_eval -> t_submit)
            # is backpressure queueing, not host work — excluded,
            # matching the native pipeline's post-acquire t_submit.
            phases = {
                "dispatch": t_begin - t_submit,
                "host_stage": (t_launch - t_begin) + (
                    (t_eval - t_flush) if ci == 0 else 0.0
                ),
            }
            t0 = time.perf_counter()
            task = loop.run_in_executor(
                self._collect_pool, self._collect_batch, handle, sub_live,
                t0, batch_id, t_flush, phases,
            )
            shard.inflight.add(task)

            def _collected(t, seq=seq, token=token, sub_live=sub_live):
                shard.inflight.discard(t)
                shard.inflight_pendings.pop(seq, None)
                shard.sem.release()
                exc = t.exception()
                if adm is not None:
                    adm.breaker.batch_finished(token, exc)
                if exc is not None:
                    _fail_futures([p for p, _c in sub_live], exc)

            task.add_done_callback(_collected)

    def _collect_batch(
        self, handle, live, t0: float, batch_id: int = 0,
        t_flush: float = 0.0, phases: Optional[dict] = None,
    ) -> None:
        """Collect-thread phase: device transfer, decode, resolve every
        future in one loop callback per loop."""
        with device_batch_span(batch_id, len(live)) as span_phases:
            auths, t_fin, t_done = _timed_call(
                self._tpu.inner.finish_check_many, handle
            )
            if self._metrics is not None:
                dt = time.perf_counter() - t0
                for hist in _latency_hists(self._metrics):
                    for _ in live:
                        hist.observe(dt)
            by_loop: Dict[object, list] = {}
            for (p, counters), auth in zip(live, auths):
                loaded = counters if p.load else []
                result = CheckResult(auth.limited, loaded, auth.limit_name)
                by_loop.setdefault(p.future.get_loop(), []).append(
                    (p.future, result)
                )
            for floop, pairs in by_loop.items():
                floop.call_soon_threadsafe(_settle_results, pairs)
            rec = self.recorder
            if phases is None:
                return
            phases["device_sync"] = t_done - t_fin
            self.chunk_planner.observe(
                phases["device_sync"],
                sum(len(counters) for _p, counters in live),
            )
            phases["unpack"] = time.perf_counter() - t_done
            span_phases(phases)
            if rec is None:
                return
            rec.record_batch(
                ((p.t_enq, p.rid, p.namespace) for p, _counters in live),
                batch_id, t_flush, phases,
            )

    def _evaluate_batch(
        self, batch: List[_RawPending]
    ) -> List[Tuple[_RawPending, List[Counter]]]:
        # Counter-plan cache first: repeat (namespace, values) identities
        # reuse their resolved Counter list and skip CEL entirely. Only
        # load_counters=False traffic is cacheable (loads mutate
        # per-counter observability fields on what would be shared
        # objects).
        cache = self.counters_cache
        requests: List[Tuple[_RawPending, List[Counter]]] = []
        misses: List[Tuple[_RawPending, Optional[tuple]]] = []
        if cache is None:
            misses = [(p, None) for p in batch]
        else:
            get = cache.get
            for p in batch:
                if p.load:
                    misses.append((p, None))
                    continue
                key = (p.namespace, tuple(p.values.items()))
                counters = get(key)
                if counters is None:
                    misses.append((p, key))
                else:
                    requests.append((p, counters))
        if not misses:
            return requests

        # Group misses by namespace; one columnar evaluation each.
        by_ns: Dict[Namespace, List[int]] = {}
        for i, (p, _key) in enumerate(misses):
            by_ns.setdefault(p.namespace, []).append(i)

        # Epoch snapshot BEFORE evaluation: put discards on mismatch, so
        # a limits bump racing this batch on another thread can never
        # file a stale counter plan under the new epoch.
        epoch = cache.epoch if cache is not None else 0
        src_cache: Dict[Limit, List[str]] = {}
        with self._eval_lock:
            for namespace, idxs in by_ns.items():
                compiler = self._compiler_for(namespace)
                evaluated = compiler.evaluate(
                    [misses[i][0].values for i in idxs]
                )
                strings = compiler.interner.strings
                for i, hits in zip(idxs, evaluated):
                    counters = []
                    for limit, tokens in hits:
                        var_sources = src_cache.get(limit)
                        if var_sources is None:
                            # limit.variables is already source-sorted
                            var_sources = [
                                v.source for v in limit.variables
                            ]
                            src_cache[limit] = var_sources
                        set_vars = {
                            src: strings[tok]
                            for src, tok in zip(var_sources, tokens)
                        }
                        counters.append(Counter(limit, set_vars))
                    p, key = misses[i]
                    if key is not None and cache is not None:
                        cache.put(key, counters, epoch)
                    requests.append((p, counters))
        return requests

    def fail_over_queued(self, decider, exc) -> None:
        """Admission-plane breaker trip: decide every queued raw request
        host-side through ``decider(counters, delta, load) ->
        Authorization`` and fail dispatched-but-uncollected batches with
        ``exc`` (their kernel may already have run). Thread-safe — the
        trip listener can fire from a collect thread; each shard's drain
        runs on its own serving loop, where that shard's queue is safe
        to touch (the ``_flush`` discipline)."""
        for shard in list(self._shards.values()):
            loop = shard.loop
            if loop is None or loop.is_closed():
                continue

            def _drain(shard=shard):
                batch, shard.pending = shard.pending, []
                if batch:
                    try:
                        evaluated = self._evaluate_batch(batch)
                    except Exception as eexc:
                        _fail_futures(batch, eexc)
                        evaluated = []
                    for p, counters in evaluated:
                        if p.future.done():
                            continue
                        try:
                            if not counters:
                                p.future.set_result(
                                    CheckResult(False, [], None)
                                )
                            else:
                                auth = decider(counters, p.delta, p.load)
                                p.future.set_result(CheckResult(
                                    auth.limited,
                                    counters if p.load else [],
                                    auth.limit_name,
                                ))
                        except Exception as dexc:
                            p.future.set_exception(dexc)
                for pendings in list(shard.inflight_pendings.values()):
                    _fail_futures(pendings, exc)

            try:
                loop.call_soon_threadsafe(_drain)
            except RuntimeError:
                pass  # loop closed between the check and the call

    async def _close_shard(self, shard: _LoopShard) -> None:
        await self._flush(shard, "shutdown")
        if shard.inflight:
            await asyncio.gather(*shard.inflight, return_exceptions=True)

    async def close(self) -> None:
        """Drain in-flight collects on every shard and release the
        worker pools."""
        cur = asyncio.get_running_loop()
        for shard in list(self._shards.values()):
            if shard.loop is cur:
                await self._close_shard(shard)
            elif not shard.loop.is_closed() and shard.loop.is_running():
                try:
                    asyncio.run_coroutine_threadsafe(
                        self._close_shard(shard), shard.loop
                    ).result(timeout=10)
                except Exception:
                    pass  # shard loop died mid-shutdown
        self._dispatch_pool.shutdown(wait=False)
        self._collect_pool.shutdown(wait=False)


def _settle_results(pairs) -> None:
    for future, result in pairs:
        if not future.done():
            future.set_result(result)


def _fail_futures(pendings, exc) -> None:
    """Fail every unresolved pending, routed through each future's own
    loop (callers may run on a different loop's thread or a pool
    thread; set_exception is only safe from the owning loop)."""
    by_loop: Dict[object, list] = {}
    for p in pendings:
        future = p.future
        if not future.done():
            by_loop.setdefault(future.get_loop(), []).append(future)

    for floop, futures in by_loop.items():
        def _do(futures=futures):
            for future in futures:
                if not future.done():
                    future.set_exception(exc)

        floop.call_soon_threadsafe(_do)
