"""TpuShardedStorage — the multi-chip counter backend.

Serves the `CounterStorage` protocol over the sharded mesh kernel
(parallel/mesh.py): the counter table is split over the mesh's "shard"
axis, the host routes every counter to its owner shard by key hash (the
ICI analogue of Redis-cluster hash-tag sharding,
/root/reference/limitador/src/storage/keys.rs:1-13), and each
``check_many`` batch is ONE ``shard_map`` launch:

- per-shard hit arrays `[n_shards, H]`, requests coupled across shards by
  ``pmin`` over the replicated request vector (a request spanning shards
  is admitted all-or-nothing — exactness preserved);
- namespaces named in ``global_namespaces`` live in the psum global
  region: one slot index shared by every shard, each shard holding a
  per-device partial, the admission base read as ``psum`` of live
  partials (the CRDT read-as-sum of cr_counter_value.rs:38-46 riding
  ICI). Over-admission for those is bounded by one in-flight batch per
  remote shard — the same contract the reference documents for its
  distributed mode (redis_cached.rs:25-41).

The existing MicroBatcher serves this class unchanged (it only needs
``check_many``), so the gRPC/HTTP planes can run multi-chip by swapping
the storage (BASELINE.json config 5, doc/topologies.md:1-37).

Scaling discipline (ISSUE 4)
----------------------------
Three rules keep throughput scaling with device count instead of against
it (BENCH_r05 measured the old path at 0.73x one device):

- **Collective-lean launches**: staging classifies each batch — psum
  only when a global-namespace hit is present, pmin only when some
  request actually spans shards (``coupled``); the common owner-sharded
  batch runs with shard-local request ids and ZERO collectives
  (parallel/mesh.py "Collective-lean variants"). Launch counts per
  variant are exported as the ``sharded_launches`` metric family.
- **Genuinely sharded staging**: hits are bucketed per shard on the host
  (memoized ``_stable_hash`` routing + the vectorized partition of
  storage.py ``_partition_positions``/``_scatter_rows``) and
  ``device_put`` with the mesh sharding, so each shard uploads only its
  own rows — never a replicated [n, H] batch.
- **In-place tables**: every table-mutating kernel donates the counter
  buffers (``sharded_check_and_update``/``sharded_update``/
  ``sharded_clear_cells``), so XLA updates the [n_shards, L+1] table in
  place instead of copying it per batch; host-side slot zeroing rides
  the donated clear kernel, not a full-table ``.at[].set`` copy.

``begin_check_many``/``finish_check_many`` split the launch from the
device->host transfer exactly like TpuStorage, so the MicroBatcher
pipelines sharded batches (and chunked dispatch overlaps sub-batches)
the same way it does single-chip ones.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core.counter import Counter
from ..core.limit import Limit
from ..storage.base import (
    Authorization,
    CounterStorage,
    StorageError,
    require_nonnegative_delta,
)
from ..storage.gcra import GcraValue, restore_cell, spent_tokens
from ..ops import kernel as K
from ..routing import RouteMemo, counter_key, stable_hash
from ..parallel.mesh import (
    ShardedCounterState,
    batch_sharding,
    make_mesh,
    make_sharded_table,
    sharded_check_and_update,
    sharded_clear_cells,
    sharded_drain_top_hits,
    sharded_update,
)
from .storage import (
    _BigLimitMixin,
    _bucket,
    _migrate_key,
    _partition_positions,
    _Request,
    _scatter_rows,
    _SlotTable,
    hot_attribution,
)

__all__ = [
    "TpuShardedStorage",
    "METRIC_FAMILIES",
    "snapshot_manifest",
    "snapshot_items",
]

#: metric families this subsystem owns (cross-checked against
#: observability/metrics.py by tools/lint.py's registry lint): per-variant
#: multi-chip launch counts + the bounded key->owner-shard memo's
#: hit/miss/eviction/size telemetry, polled off ``launch_stats()`` at
#: render time.
METRIC_FAMILIES = (
    "sharded_launches",
    "sharded_route_memo_hits",
    "sharded_route_memo_misses",
    "sharded_route_memo_evictions",
    "sharded_route_memo_size",
)

#: sharded_launches label values: lean = no collective at all, coupled =
#: pmin request coupling only, global = psum global region present.
LAUNCH_VARIANTS = ("lean", "coupled", "global")

_INT32_MAX = int(np.iinfo(np.int32).max)


# Ownership hash, shared with the ingress-tier routers (routing.py) so
# every layer agrees about who owns a key. Kept under the historical
# name — snapshots re-route keys through it on restore.
_stable_hash = stable_hash


class _ShardedHandle:
    """In-flight sharded batch: kernel launched, device->host transfer
    pending. Produced by ``begin_check_many``, consumed by
    ``finish_check_many`` — the sharded analogue of storage.py's
    ``_CheckHandle``, carrying the flat staging columns so decode is a
    vectorized gather instead of per-hit Python."""

    __slots__ = (
        "requests", "result", "coupled", "seq", "now", "shard_ids", "pos",
        "slot_col", "glob_col", "j_l", "starts", "adjust_by_req", "home",
        "local_ids", "fresh_by_req", "big_by_req", "big_projected",
        "watch_touches",
    )

    def __init__(self, requests, result, coupled, seq, now, shard_ids, pos,
                 slot_col, glob_col, j_l, starts, adjust_by_req, home,
                 local_ids, fresh_by_req, big_by_req, big_projected,
                 watch_touches):
        self.requests = requests
        self.result = result
        self.coupled = coupled
        self.seq = seq
        self.now = now
        self.shard_ids = shard_ids
        self.pos = pos
        self.slot_col = slot_col
        self.glob_col = glob_col
        self.j_l = j_l
        self.starts = starts
        self.adjust_by_req = adjust_by_req
        self.home = home            # lean mode: owner shard per request
        self.local_ids = local_ids  # lean mode: shard-local request id
        self.fresh_by_req = fresh_by_req
        self.big_by_req = big_by_req
        self.big_projected = big_projected
        self.watch_touches = watch_touches


class TpuShardedStorage(_BigLimitMixin, CounterStorage):
    supports_token_bucket = True  # device bucket lane / exact host path

    def _is_big(self, counter: Counter) -> bool:
        # A TAT cell cannot be a psum global partial: token buckets in
        # global namespaces stay on the node-local exact host path.
        # Owner-sharded buckets ride the device lane like any counter.
        if (
            counter.limit.policy == "token_bucket"
            and counter.namespace in self._global_ns
        ):
            return True
        return _BigLimitMixin._is_big(self, counter)

    def __init__(
        self,
        mesh=None,
        local_capacity: int = 1 << 17,
        cache_size: Optional[int] = None,
        global_namespaces: Sequence[str] = (),
        global_region: int = 1024,
        clock=time.time,
    ):
        """``local_capacity`` sizes each shard's table (8 bytes/counter of
        HBM per shard); slots below ``global_region`` are reserved for
        psum-replicated global counters. ``cache_size`` caps qualified
        counters across the whole mesh."""
        self._mesh = mesh if mesh is not None else make_mesh()
        self._n = self._mesh.shape["shard"]
        if global_region >= local_capacity:
            raise ValueError("global_region must be < local_capacity")
        self._lock = threading.RLock()
        self._clock = clock
        self._local_capacity = int(local_capacity)
        self._global_region = int(global_region)
        self._global_ns = set(global_namespaces)
        total_local = self._n * (local_capacity - global_region)
        self._cache_size = int(cache_size) if cache_size else total_local
        self._per_shard_cache = max(self._cache_size // self._n, 1)
        self._scratch = self._local_capacity  # padding slot (row L)
        self._tables: List[_SlotTable] = []
        self._gtable = _SlotTable(self._global_region)
        self._rr = 0  # round-robin shard for global-counter deltas
        # Memoized key -> owner shard (the crc32 hash is pure; recomputing
        # repr+crc per hit was the staging pass's hot spot). LRU-bounded
        # (routing.RouteMemo): the old dict grew one entry per unique key
        # — unbounded at the 1M+ key regime this storage exists for.
        self._shard_memo = RouteMemo(4 * self._cache_size)
        # Batch input sharding: device_put hit columns with this so each
        # shard uploads only its own rows.
        self._sharding = batch_sharding(self._mesh)
        # Pipelining bookkeeping (the TpuStorage discipline): batch seq +
        # last-touch seq of watched slots, keyed (shard, slot) for locals
        # and (-1, slot) for the psum global region.
        self._seq = 0
        self._watched: Dict[Tuple[int, int], int] = {}
        # Per-variant launch tallies (the sharded_launches families).
        self._launches: Dict[str, int] = dict.fromkeys(LAUNCH_VARIANTS, 0)
        # Host-side fallback for max_value > device cap (_BigLimitMixin).
        self._init_big(self._cache_size)
        self._reset_tables()
        self._state = make_sharded_table(self._mesh, self._local_capacity)
        self._epoch = clock()
        #: pod-mode snapshot manifest (ISSUE 15): the server sets
        #: ``{"owned_shards": [lo, hi), "topology": {...}}`` so every
        #: checkpoint records WHICH global shard block this host owned
        #: when it was taken — the key a post-membership-change restore
        #: re-maps slices by (``snapshot_manifest``/``snapshot_items``).
        self.snapshot_meta: Optional[dict] = None

    def _reset_tables(self) -> None:
        self._tables = []
        for _ in range(self._n):
            t = _SlotTable(self._local_capacity)
            # Shard-local slots live in [global_region, local_capacity).
            t.free = list(
                range(self._local_capacity - 1, self._global_region - 1, -1)
            )
            self._tables.append(t)
        self._gtable = _SlotTable(self._global_region)

    # -- time ---------------------------------------------------------------

    def _now_ms(self) -> int:
        now = int((self._clock() - self._epoch) * 1000)
        if now > (1 << 30):
            shift = now - 1000
            self._state = ShardedCounterState(
                self._state.values,
                K.rebase_epoch_chunked(self._state.expiry_ms, shift),
                self._state.hits,
            )
            self._epoch += shift / 1000.0
            now -= shift
        return now

    # -- slot routing -------------------------------------------------------

    # Routed identity, shared with the ingress-tier routers
    # (routing.counter_key): both layers must hash the same bytes.
    _key_of = staticmethod(counter_key)

    def _is_global(self, counter: Counter) -> bool:
        return counter.namespace in self._global_ns

    def _clear_rows(self, rows: np.ndarray) -> None:
        """Zero per-shard cell lists via the donated clear kernel
        (``rows`` is [n, k], scratch-padded) — in-place on device, no
        full-table copy."""
        k = _bucket(rows.shape[1])
        padded = np.full((self._n, k), self._scratch, np.int32)
        padded[:, : rows.shape[1]] = rows
        self._state = sharded_clear_cells(self._mesh, self._state, padded)

    def _zero_global_slots(self, slots: List[int]) -> None:
        """A recycled global slot must not inherit stale partials on any
        shard (the kernel's psum base reads the whole global region, not
        just table-reachable cells)."""
        idx = np.asarray(slots, np.int32)
        self._clear_rows(np.broadcast_to(idx, (self._n, idx.shape[0])))

    def _evict_local(self, table: _SlotTable) -> None:
        if not table.qualified:
            raise StorageError("TPU shard table full (no evictable slots)")
        key, slot = next(iter(table.qualified.items()))
        table.release(slot, key, qualified=True)
        table.evictions += 1

    def _evict_global(self) -> None:
        if not self._gtable.qualified:
            raise StorageError("TPU global region full (no evictable slots)")
        key, slot = next(iter(self._gtable.qualified.items()))
        self._gtable.release(slot, key, qualified=True)
        self._gtable.evictions += 1
        self._zero_global_slots([slot])

    def _slot_for(
        self, counter: Counter, create: bool
    ) -> Tuple[Optional[int], Optional[int], bool, bool]:
        """Return (shard, slot, fresh, is_global). Global counters return
        shard=None (the caller picks an application shard)."""
        key = self._key_of(counter)
        qualified = counter.is_qualified()
        if self._is_global(counter):
            slot = self._gtable.lookup(key, qualified)
            if slot is not None:
                return None, slot, False, True
            if not create:
                return None, None, False, True
            if qualified and len(self._gtable.qualified) >= self._global_region:
                self._evict_global()
            if not self._gtable.free:
                self._evict_global()
            slot = self._gtable.alloc()
            if qualified:
                self._gtable.qualified[key] = slot
            else:
                self._gtable.simple[key] = slot
            self._gtable.info[slot] = (key, counter.key())
            return None, slot, True, True
        shard = self._shard_memo.get(key)
        if shard is None:
            shard = _stable_hash(key) % self._n
            self._shard_memo.put(key, shard)
        table = self._tables[shard]
        slot = table.lookup(key, qualified)
        if slot is not None:
            return shard, slot, False, False
        if not create:
            return shard, None, False, False
        if qualified:
            while len(table.qualified) >= self._per_shard_cache:
                self._evict_local(table)
        if not table.free:
            self._evict_local(table)
        slot = table.alloc()
        if qualified:
            table.qualified[key] = slot
        else:
            table.simple[key] = slot
        table.info[slot] = (key, counter.key())
        return shard, slot, True, False

    def _app_shard(self) -> int:
        """Application shard for a global-counter delta (any shard works —
        the read is psum); round-robin spreads partials."""
        s = self._rr
        self._rr = (self._rr + 1) % self._n
        return s

    def launch_stats(self) -> dict:
        """Cumulative multi-chip launch counts per collective variant
        (the ``sharded_launches`` metric family, polled baseline-
        converted off library_stats at render time): a hot path that
        is mostly ``coupled``/``global`` instead of ``lean`` means the
        limits layout is forcing collectives onto every batch. Rides
        along: the route-memo's hit/miss/eviction counters (a miss-
        heavy memo means the LRU cap is thrashing under the live key
        cardinality)."""
        with self._lock:
            stats = {"sharded_launches": dict(self._launches)}
            stats.update(self._shard_memo.stats())
            return stats

    def device_stats(self) -> dict:
        """Per-shard table stats for /debug/stats and the Prometheus
        shard gauges: one entry per shard-local table (capacity = the
        shard-local slot range) plus the replicated psum global region."""
        with self._lock:
            shards = [{
                "shard": str(i),
                "occupied": len(t.info),
                "capacity": self._local_capacity - self._global_region,
                "evictions": t.evictions,
                "collisions": t.collisions,
            } for i, t in enumerate(self._tables)]
            if self._global_region:
                shards.append({
                    "shard": "global",
                    "occupied": len(self._gtable.info),
                    "capacity": self._global_region,
                    "evictions": self._gtable.evictions,
                    "collisions": self._gtable.collisions,
                })
            return {"shards": shards}

    def drain_hot_slots(self, k: int = 64) -> List[dict]:
        """Sharded heavy-hitter drain (ISSUE 8): one per-shard top-k
        kernel (no collective; 2*k ints per shard cross the link), then
        host-side attribution through the per-shard slot tables. A psum
        global counter's traffic lands in each hitting shard's
        accumulator row — those counts merge here by slot, attributed
        through the global table with the read-as-sum value. Returns the
        merged records hottest-first (at most k)."""
        with self._lock:
            hits = self._state.hits
            if hits is None or k <= 0:
                return []
            now_ms = self._now_ms()
            kk = min(int(k), self._local_capacity)
            new_hits, counts, slots = sharded_drain_top_hits(
                self._mesh, hits, kk
            )
            self._state = ShardedCounterState(
                self._state.values, self._state.expiry_ms, new_hits
            )
            counts = np.asarray(counts)
            slots = np.asarray(slots)
            out: List[dict] = []
            g_counts: Dict[int, int] = {}
            loc_sh: List[int] = []
            loc_sl: List[int] = []
            loc_count: List[int] = []
            for s in range(self._n):
                for j in range(counts.shape[1]):
                    c = int(counts[s, j])
                    if c <= 0:
                        continue
                    slot = int(slots[s, j])
                    if slot < self._global_region:
                        g_counts[slot] = g_counts.get(slot, 0) + c
                    else:
                        loc_sh.append(s)
                        loc_sl.append(slot)
                        loc_count.append(c)
            if loc_sl:
                # Gather ONLY the drained coordinates — never the table.
                sh = np.asarray(loc_sh, np.int32)
                sl = np.asarray(loc_sl, np.int32)
                vals = np.asarray(self._state.values[sh, sl])
                exps = np.asarray(self._state.expiry_ms[sh, sl])
                for i in range(sh.shape[0]):
                    shard, slot = int(sh[i]), int(sl[i])
                    record = {
                        "slot": slot, "shard": shard,
                        "count": loc_count[i],
                    }
                    entry = self._tables[shard].info.get(slot)
                    if entry is not None:
                        ttl = max(int(exps[i]) - now_ms, 0)
                        value = int(vals[i]) if ttl > 0 else 0
                        record.update(
                            hot_attribution(entry[1], value, ttl)
                        )
                    out.append(record)
            if g_counts:
                gsl = np.asarray(sorted(g_counts), np.int32)
                gvals = np.asarray(self._state.values[:, gsl])
                gexps = np.asarray(self._state.expiry_ms[:, gsl])
                live = gexps > now_ms
                value_sum = (gvals * live).sum(axis=0)
                ttls = np.maximum(gexps.max(axis=0) - now_ms, 0)
                for i, slot in enumerate(gsl.tolist()):
                    record = {
                        "slot": int(slot), "shard": "global",
                        "count": g_counts[int(slot)],
                    }
                    entry = self._gtable.info.get(int(slot))
                    if entry is not None:
                        record.update(hot_attribution(
                            entry[1], int(value_sum[i]), int(ttls[i])
                        ))
                    out.append(record)
            out.sort(key=lambda r: -r["count"])
            return out[:kk]

    # -- the shared batched check path --------------------------------------

    def begin_check_many(self, requests: List[_Request]) -> "_ShardedHandle":
        """Stage, partition per shard, and LAUNCH one batch without
        waiting on the device (the TpuStorage begin/finish discipline, so
        the batcher overlaps batch N+1's staging with batch N's round
        trip). Table mutations serialize under the lock in call order,
        which is also device program order.

        Staging classifies the batch: ``coupled`` when any request's
        device hits span shards (pmin rides along), ``has_global`` when
        any hit lands in the psum region — otherwise the launch is the
        collective-free lean variant with shard-local request ids.
        Counters with max_value beyond the device cap are decided
        host-side here, exactly as in TpuStorage.begin_check_many."""
        import jax

        for request in requests:
            require_nonnegative_delta(request.delta)
        n = self._n
        # Flat per-hit columns (Python lists; one C-level conversion +
        # one vectorized per-shard scatter after the loop).
        shard_l: List[int] = []
        slot_l: List[int] = []
        delta_l: List[int] = []
        max_l: List[int] = []
        win_l: List[int] = []
        req_l: List[int] = []
        fresh_l: List[bool] = []
        bucket_l: List[bool] = []
        glob_l: List[bool] = []
        j_l: List[int] = []
        with self._lock:
            now_ms = self._now_ms()
            now = self._clock()
            self._seq += 1
            seq = self._seq
            watched = self._watched
            watch_touches: List[Tuple[int, int]] = []
            fresh_by_req: List[List[Tuple[int, Counter, int, int, bool]]] = []
            big_by_req: List[list] = []
            big_projected: List[Tuple[tuple, int]] = []
            starts: List[int] = []      # flat-hit range start per request
            adjust_by_req: List[int] = []
            home_l: List[int] = []      # owner shard per request (-1 none)
            coupled = False
            slot_for = self._slot_for
            lane_of = self._lane_of
            is_big = self._is_big
            for r, request in enumerate(requests):
                starts.append(len(slot_l))
                raw_delta = int(request.delta)
                delta = min(raw_delta, K.MAX_DELTA_CAP)
                bigs, big_failed, projected = self._eval_big_hits(
                    request.ordered, raw_delta, now
                )
                big_projected.extend(projected)
                dev_delta = 0 if big_failed else delta
                adjust_by_req.append(delta if big_failed else 0)
                home = -1
                fresh_hits: List[Tuple[int, Counter, int, int, bool]] = []
                for j, c in enumerate(request.ordered):
                    if is_big(c):
                        continue
                    shard, slot, is_fresh, is_g = slot_for(c, create=True)
                    if is_g:
                        shard = self._app_shard()
                    if home < 0:
                        home = shard
                    elif shard != home:
                        coupled = True
                    win, is_bucket = lane_of(c)
                    shard_l.append(shard)
                    slot_l.append(slot)
                    delta_l.append(dev_delta)
                    max_l.append(min(c.max_value, K.MAX_VALUE_CAP))
                    win_l.append(win)
                    req_l.append(r)
                    fresh_l.append(is_fresh)
                    bucket_l.append(is_bucket)
                    glob_l.append(is_g)
                    j_l.append(j)
                    wkey = (-1, slot) if is_g else (shard, slot)
                    if is_fresh:
                        fresh_hits.append((j, c, shard, slot, is_g))
                        watched[wkey] = seq
                        watch_touches.append(wkey)
                    elif wkey in watched:
                        # A later batch re-used a slot an earlier in-flight
                        # batch may want to release: the re-use wins.
                        watched[wkey] = seq
                        watch_touches.append(wkey)
                home_l.append(home)
                fresh_by_req.append(fresh_hits)
                big_by_req.append(bigs)
            starts.append(len(slot_l))

            R = len(requests)
            shard_ids = np.asarray(shard_l, np.int32)
            counts, pos = _partition_positions(shard_ids, n)
            max_count = int(counts.max(initial=0))
            if coupled:
                # n*H must cover every request id (big-only requests
                # still consume an id even with zero device hits).
                H = _bucket(max(max_count, (R + n - 1) // n, 1))
                req_col = np.asarray(req_l, np.int32)
                req_fill = n * H - 1
                home = local_ids = None
            else:
                H = _bucket(max(max_count, 1))
                # Shard-local request ids: dense per shard, assigned in
                # request order (nondecreasing within each shard's rows).
                home = np.asarray(home_l, np.int32)
                mask = home >= 0
                local_ids = np.full(R, H - 1, np.int32)
                if mask.any():
                    _lc, lpos = _partition_positions(home[mask], n)
                    local_ids[mask] = lpos.astype(np.int32)
                req_col = local_ids[np.asarray(req_l, np.intp)]
                req_fill = H - 1
            slot_col = np.asarray(slot_l, np.int32)
            glob_col = np.asarray(glob_l, bool)
            has_global = bool(glob_col.any())
            cols = _scatter_rows(shard_ids, pos, n, H, (
                (slot_col, self._scratch, np.int32),
                (delta_l, 0, np.int32),
                (max_l, _INT32_MAX, np.int32),
                (win_l, 0, np.int32),
                (req_col, req_fill, np.int32),
                (fresh_l, False, bool),
                (bucket_l, False, bool),
                (glob_col, False, bool),
            ))
            try:
                # Sharded upload: each shard receives only its own rows.
                cols = jax.device_put(tuple(cols), self._sharding)
                self._state, result = sharded_check_and_update(
                    self._mesh, self._state, *cols, np.int32(now_ms),
                    global_region=self._global_region,
                    coupled=coupled, has_global=has_global,
                )
            except BaseException:
                # Projection reservations must not leak on a failed launch.
                self._unproject_big(big_projected)
                raise
            self._launches[
                "global" if has_global
                else ("coupled" if coupled else "lean")
            ] += 1
        return _ShardedHandle(
            requests, result, coupled, seq, now, shard_ids, pos, slot_col,
            glob_col, np.asarray(j_l, np.int32), np.asarray(starts, np.intp),
            adjust_by_req, home, local_ids, fresh_by_req, big_by_req,
            big_projected, watch_touches,
        )

    def finish_check_many(
        self, handle: "_ShardedHandle"
    ) -> List[Authorization]:
        """Transfer and decode one in-flight batch: load_counters side
        effects, first-limited naming, and the non-load early-return slot
        release (guarded by the watched-slot seq so a later in-flight
        batch's re-use of the slot wins — same contract as
        TpuStorage.finish_check_many)."""
        import jax

        result = handle.result
        try:
            admitted, hit_ok, remaining, ttl_ms = jax.device_get((
                result.admitted, result.hit_ok, result.remaining,
                result.ttl_ms,
            ))
        except BaseException:
            with self._lock:
                self._unproject_big(handle.big_projected)
                # The watch entries must not outlive the batch either: a
                # stale seq would suppress every later batch's release
                # of these slots (leaking qualified slots under repeated
                # device faults).
                watched = self._watched
                for wkey in handle.watch_touches:
                    if watched.get(wkey) == handle.seq:
                        del watched[wkey]
            raise

        requests = handle.requests
        shard_ids, pos = handle.shard_ids, handle.pos
        starts = handle.starts
        j_l = handle.j_l
        R = len(requests)
        # Vectorized flat views (one fancy gather per output, not a
        # Python pair loop per hit).
        ok_flat = hit_ok[shard_ids, pos]
        rem_flat = ttl_flat = None
        if any(request.load for request in requests):
            rem_flat = remaining[shard_ids, pos]
            ttl_flat = ttl_ms[shard_ids, pos]
        if handle.coupled:
            adm_by_req = admitted[:R]
        else:
            adm_by_req = np.ones(R, bool)
            mask = handle.home >= 0
            if mask.any():
                adm_by_req[mask] = admitted[
                    handle.home[mask], handle.local_ids[mask]
                ]
        use_counts = None  # computed lazily, only when a release is due

        auths: List[Authorization] = []
        big_applies: List[Tuple[tuple, int, int]] = []
        releases: List[Tuple[Counter, int, int, bool]] = []
        for r, request in enumerate(requests):
            s0, s1 = int(starts[r]), int(starts[r + 1])
            bigs = handle.big_by_req[r]
            dev_ok = bool(adm_by_req[r]) if s1 > s0 else True
            big_ok = all(ok for _j, ok, *_rest in bigs)
            if request.load:
                adjust = handle.adjust_by_req[r]
                for i in range(s0, s1):
                    c = request.ordered[int(j_l[i])]
                    c.remaining = max(int(rem_flat[i]) - adjust, 0)
                    c.expires_in = float(ttl_flat[i]) / 1000.0
                for j, _ok, rem, ttl, _key, _c, _d in bigs:
                    c = request.ordered[j]
                    c.remaining = rem
                    c.expires_in = ttl
            if dev_ok and big_ok:
                auths.append(Authorization.OK)
                for _j, _ok, _rem, _ttl, key, c, d in bigs:
                    big_applies.append((key, d, c.window_seconds))
                continue
            oks_by_j = {
                int(j_l[i]): bool(ok_flat[i]) for i in range(s0, s1)
            }
            for j, ok, *_rest in bigs:
                oks_by_j[j] = ok
            limited_js = [j for j, ok in oks_by_j.items() if not ok]
            first = min(limited_js) if limited_js else 0
            auths.append(
                Authorization.limited_by(request.ordered[first].limit.name)
            )
            if not request.load:
                # Non-load early-return semantics (in_memory.rs:110-133):
                # drop qualified slots allocated past the first limited
                # hit, when no other hit in the batch shares them.
                for j, c, shard, slot, is_g in handle.fresh_by_req[r]:
                    if j <= first:
                        continue
                    if use_counts is None:
                        use_counts = self._slot_use_counts(
                            shard_ids, handle.slot_col, handle.glob_col
                        )
                    use = (-slot - 1) if is_g else (shard << 32) + slot
                    if use_counts.get(use) == 1:
                        releases.append((c, shard, slot, is_g))
        with self._lock:
            self._unproject_big(handle.big_projected)
            self._apply_big(big_applies, handle.now)
            watched = self._watched
            for c, shard, slot, is_g in releases:
                wkey = (-1, slot) if is_g else (shard, slot)
                if watched.get(wkey) != handle.seq:
                    continue
                # The table must still map this key to this slot — an
                # intervening delete/evict/clear means the slot was
                # already freed (releasing again would double-free it).
                key = self._key_of(c)
                qualified = c.is_qualified()
                table = self._gtable if is_g else self._tables[shard]
                mapped = (
                    table.qualified.get(key) == slot
                    if qualified else table.simple.get(key) == slot
                )
                if mapped:
                    self._release(c, shard, slot, is_g)
            for wkey in handle.watch_touches:
                if watched.get(wkey) == handle.seq:
                    del watched[wkey]
        return auths

    @staticmethod
    def _slot_use_counts(shard_ids, slot_col, glob_col) -> Dict[int, int]:
        """Batch-wide use count per device cell, as a composite-int map
        (negative = global slot). Vectorized; built only when a non-load
        limited request actually has fresh slots to consider releasing."""
        comp = np.where(
            glob_col,
            -(slot_col.astype(np.int64) + 1),
            shard_ids.astype(np.int64) * (1 << 32) + slot_col,
        )
        uniq, cnt = np.unique(comp, return_counts=True)
        return dict(zip(uniq.tolist(), cnt.tolist()))

    def check_many(self, requests: List[_Request]) -> List[Authorization]:
        """One sharded launch deciding a batch of requests in list order
        (same exactness contract as TpuStorage.check_many; cross-shard
        requests couple via pmin when present)."""
        return self.finish_check_many(self.begin_check_many(requests))

    def _release(self, counter: Counter, shard: int, slot: int, is_g: bool):
        key = self._key_of(counter)
        if is_g:
            self._gtable.release(slot, key, counter.is_qualified())
            self._zero_global_slots([slot])
        else:
            self._tables[shard].release(slot, key, counter.is_qualified())

    # -- host reads ---------------------------------------------------------

    def _read_value(
        self, shard: Optional[int], slot: int, is_g: bool, now_ms: int
    ) -> Tuple[int, int]:
        """(live value, ttl_ms) — psum of live partials for global slots."""
        if is_g:
            vals = np.asarray(self._state.values[:, slot])
            exps = np.asarray(self._state.expiry_ms[:, slot])
            live = exps > now_ms
            value = int(vals[live].sum())
            ttl = int(exps.max() - now_ms) if live.any() else 0
            return value, max(ttl, 0)
        v = int(self._state.values[shard, slot])
        e = int(self._state.expiry_ms[shard, slot])
        if e <= now_ms:
            return 0, 0
        return v, e - now_ms

    # -- CounterStorage ------------------------------------------------------

    def is_within_limits(self, counter: Counter, delta: int) -> bool:
        with self._lock:
            now_ms = self._now_ms()
            if self._is_big(counter):
                entry = self._big.get(self._key_of(counter))
                value = (
                    entry[0].value_at(self._clock())
                    if entry is not None else 0
                )
                return value + delta <= counter.max_value
            shard, slot, _f, is_g = self._slot_for(counter, create=False)
            if slot is None:
                value = 0
            else:
                value, ttl = self._read_value(shard, slot, is_g, now_ms)
                if counter.limit.policy == "token_bucket":
                    # Bucket cells: ttl is base_rel = max(TAT - now, 0);
                    # spent tokens derive from it (values lane unspecified).
                    value = spent_tokens(
                        counter.max_value, counter.window_seconds, ttl
                    )
        return value + delta <= counter.max_value

    def add_counter(self, limit: Limit) -> None:
        if not limit.variables:
            with self._lock:
                counter = Counter(limit, {})
                if self._is_big(counter):
                    self._big_cell(counter, self._key_of(counter))
                else:
                    shard, slot, fresh, is_g = self._slot_for(
                        counter, create=True
                    )
                    if fresh and not is_g:
                        # No kernel batch follows: clear a recycled local
                        # cell (global slots are zeroed at release —
                        # _zero_global_slots — so only locals can carry a
                        # stale occupant here).
                        rows = np.full((self._n, 1), self._scratch, np.int32)
                        rows[shard, 0] = slot
                        self._clear_rows(rows)

    def update_counter(self, counter: Counter, delta: int) -> None:
        self.apply_deltas([(counter, delta)])

    def check_and_update(
        self, counters: List[Counter], delta: int, load_counters: bool
    ) -> Authorization:
        if not counters:
            return Authorization.OK
        return self.check_many([_Request(counters, delta, load_counters)])[0]

    def apply_deltas(self, items):
        """Unconditional batched increments (the Report/update path and the
        write-behind authority role): one ``sharded_update`` launch — the
        same saturating scatter-add as the single-chip authority — then two
        batched gathers (one for shard-local slots, one for the global
        region) for the authoritative values."""
        for _counter, delta in items:
            require_nonnegative_delta(delta)
        import jax

        with self._lock:
            now_ms = self._now_ms()
            now = self._clock()
            # Flat staging columns (the begin_check_many discipline).
            app_l: List[int] = []
            slot_l: List[int] = []
            delta_l: List[int] = []
            win_l: List[int] = []
            fresh_l: List[bool] = []
            bucket_l: List[bool] = []
            # loc: (shard, slot, is_global, counter) or ("big", value, ttl)
            locs: List[tuple] = []
            for counter, delta in items:
                if self._is_big(counter):
                    cell = self._big_cell(counter, self._key_of(counter))
                    value = cell.update(
                        int(delta), counter.window_seconds, now
                    )
                    locs.append(("big", value, cell.ttl(now)))
                    continue
                shard, slot, is_fresh, is_g = self._slot_for(
                    counter, create=True
                )
                win, is_bucket = self._lane_of(counter)
                app_l.append(self._app_shard() if is_g else shard)
                slot_l.append(slot)
                delta_l.append(min(int(delta), K.MAX_DELTA_CAP))
                win_l.append(win)
                fresh_l.append(is_fresh)
                bucket_l.append(is_bucket)
                locs.append((shard, slot, is_g, counter))
            n = self._n
            app_ids = np.asarray(app_l, np.int32)
            counts, pos = _partition_positions(app_ids, n)
            H = _bucket(max(int(counts.max(initial=0)), 1))
            cols = _scatter_rows(app_ids, pos, n, H, (
                (slot_l, self._scratch, np.int32),
                (delta_l, 0, np.int32),
                (win_l, 0, np.int32),
                (fresh_l, False, bool),
                (bucket_l, False, bool),
            ))
            cols = jax.device_put(tuple(cols), self._sharding)
            self._state = sharded_update(
                self._mesh, self._state, *cols, np.int32(now_ms),
            )
            # Batched authoritative reads: one gather per slot family.
            dev_locs = [loc for loc in locs if loc[0] != "big"]
            lsh = np.asarray(
                [s for s, _sl, g, _c in dev_locs if not g], np.int32
            )
            lsl = np.asarray(
                [sl for _s, sl, g, _c in dev_locs if not g], np.int32
            )
            gsl = np.asarray(
                sorted({sl for _s, sl, g, _c in dev_locs if g}), np.int32
            )
            lv = le = gv = ge = None
            if lsh.size:
                lv = np.asarray(self._state.values[lsh, lsl])
                le = np.asarray(self._state.expiry_ms[lsh, lsl])
            if gsl.size:
                gv = np.asarray(self._state.values[:, gsl])
                ge = np.asarray(self._state.expiry_ms[:, gsl])
            gpos = {int(sl): i for i, sl in enumerate(gsl)}
            out = []
            li = 0
            for loc in locs:
                if loc[0] == "big":
                    _tag, value, ttl_s = loc
                    out.append((value, ttl_s))
                    continue
                shard, slot, is_g, counter = loc
                if is_g:
                    col = gpos[slot]
                    live = ge[:, col] > now_ms
                    value = int(gv[live, col].sum())
                    ttl = (
                        max(int(ge[:, col].max()) - now_ms, 0)
                        if live.any() else 0
                    )
                else:
                    ttl = max(int(le[li]) - now_ms, 0)
                    if counter.limit.policy == "token_bucket":
                        value = spent_tokens(
                            counter.max_value, counter.window_seconds, ttl
                        )
                    else:
                        value = int(lv[li]) if le[li] > now_ms else 0
                    li += 1
                out.append((value, ttl / 1000.0))
        return out

    def get_counters(self, limits: Set[Limit]) -> Set[Counter]:
        out: Set[Counter] = set()
        with self._lock:
            now_ms = self._now_ms()
            namespaces = {limit.namespace for limit in limits}
            g_matching = [
                (slot, counter)
                for slot, (_key, counter) in self._gtable.info.items()
                if counter.limit in limits or counter.namespace in namespaces
            ]
            l_matching = [
                (shard, slot, counter)
                for shard, table in enumerate(self._tables)
                for slot, (_key, counter) in table.info.items()
                if counter.limit in limits or counter.namespace in namespaces
            ]
            # Device-side gathers of only the matching cells: O(matching)
            # transferred, not the whole [n_shards, capacity] table.
            if g_matching:
                gsl = np.asarray([s for s, _c in g_matching], np.int32)
                gv = np.asarray(self._state.values[:, gsl])
                ge = np.asarray(self._state.expiry_ms[:, gsl])
                for col, (_slot, counter) in enumerate(g_matching):
                    live = ge[:, col] > now_ms
                    if not live.any():
                        continue
                    c = counter.key()
                    c.remaining = c.max_value - int(gv[live, col].sum())
                    c.expires_in = (int(ge[:, col].max()) - now_ms) / 1000.0
                    out.add(c)
            if l_matching:
                lsh = np.asarray([s for s, _sl, _c in l_matching], np.int32)
                lsl = np.asarray([sl for _s, sl, _c in l_matching], np.int32)
                lv = np.asarray(self._state.values[lsh, lsl])
                le = np.asarray(self._state.expiry_ms[lsh, lsl])
                for i, (_shard, _slot, counter) in enumerate(l_matching):
                    ttl = int(le[i]) - now_ms
                    if ttl <= 0:
                        continue
                    c = counter.key()
                    if c.limit.policy == "token_bucket":
                        c.remaining = c.max_value - spent_tokens(
                            c.max_value, c.window_seconds, ttl
                        )
                    else:
                        c.remaining = c.max_value - int(lv[i])
                    c.expires_in = ttl / 1000.0
                    out.add(c)
            self._emit_big_counters(limits, namespaces, self._clock(), out)
        return out

    def delete_counters(self, limits: Set[Limit]) -> None:
        with self._lock:
            doomed_global: List[int] = []
            for slot, (key, counter) in list(self._gtable.info.items()):
                if counter.limit in limits:
                    self._gtable.release(slot, key, counter.is_qualified())
                    doomed_global.append(slot)
            shard_idx: List[int] = []
            slot_idx: List[int] = []
            for shard, table in enumerate(self._tables):
                for slot, (key, counter) in list(table.info.items()):
                    if counter.limit in limits:
                        table.release(slot, key, counter.is_qualified())
                        shard_idx.append(shard)
                        slot_idx.append(slot)
            if doomed_global:
                self._zero_global_slots(doomed_global)
            if shard_idx:
                si = np.asarray(shard_idx, np.int32)
                li = np.asarray(slot_idx, np.int32)
                counts, pos = _partition_positions(si, self._n)
                (rows,) = _scatter_rows(
                    si, pos, self._n, max(int(counts.max(initial=0)), 1),
                    ((li, self._scratch, np.int32),),
                )
                self._clear_rows(rows)
            self._delete_big(limits)

    def clear(self) -> None:
        with self._lock:
            self._reset_tables()
            self._clear_big()
            self._watched.clear()
            self._state = make_sharded_table(
                self._mesh, self._local_capacity
            )

    # -- checkpoint / resume -------------------------------------------------

    def snapshot(self, path: str) -> None:
        """Sparse checkpoint of the sharded table: occupied shard-local
        cells + the global region's per-shard partials + the host key
        space (same reopen semantics as TpuStorage.snapshot). When the
        server set :attr:`snapshot_meta` (pod mode, ISSUE 15) the
        payload additionally carries the OWNED-SHARD-RANGE manifest —
        ``owned_shards``/``topology`` — so a restore after a membership
        change can map slices to the new topology (``snapshot_items``)
        instead of silently loading the wrong host's table."""
        import pickle

        with self._lock:
            locs = [
                (shard, slot)
                for shard, table in enumerate(self._tables)
                for slot in table.info
            ]
            gslots = np.asarray(sorted(self._gtable.info), np.int32)
            if locs:
                lsh = np.asarray([s for s, _ in locs], np.int32)
                lsl = np.asarray([sl for _, sl in locs], np.int32)
                lvalues = np.asarray(self._state.values[lsh, lsl])
                lexpiry = np.asarray(self._state.expiry_ms[lsh, lsl])
            else:
                lvalues = lexpiry = np.zeros(0, np.int32)
            if gslots.size:
                gvalues = np.asarray(self._state.values[:, gslots])
                gexpiry = np.asarray(self._state.expiry_ms[:, gslots])
            else:
                gvalues = gexpiry = np.zeros((self._n, 0), np.int32)
            payload = {
                "format": 1,
                "n_shards": self._n,
                "local_capacity": self._local_capacity,
                "global_region": self._global_region,
                "global_namespaces": sorted(self._global_ns),
                "cache_size": self._cache_size,
                "epoch": self._epoch,
                "locs": locs,
                "lvalues": lvalues,
                "lexpiry": lexpiry,
                "gslots": gslots,
                "gvalues": gvalues,
                "gexpiry": gexpiry,
                "tables": [t.dump() for t in self._tables],
                "gtable": self._gtable.dump(),
                "big": {
                    key: (
                        (cell.tat, cell.scale, counter)
                        if isinstance(cell, GcraValue)
                        else (cell.value_raw, cell.expiry, counter)
                    )
                    for key, (cell, counter) in self._big.items()
                },
            }
            if self.snapshot_meta:
                payload["manifest"] = dict(self.snapshot_meta)
        with open(path, "wb") as f:
            pickle.dump(payload, f)

    @classmethod
    def restore(
        cls, path: str, mesh=None, cache_size=None, clock=time.time
    ) -> "TpuShardedStorage":
        """``cache_size`` (unlike capacity/region/namespaces, which govern
        key routing and must match the checkpoint) may be overridden."""
        import pickle

        with open(path, "rb") as f:
            data = pickle.load(f)
        self = cls(
            mesh=mesh,
            local_capacity=data["local_capacity"],
            cache_size=cache_size or data["cache_size"],
            global_namespaces=data["global_namespaces"],
            global_region=data["global_region"],
            clock=clock,
        )
        if self._n != data["n_shards"]:
            raise StorageError(
                f"snapshot was taken on {data['n_shards']} shards, mesh "
                f"has {self._n} (key routing would change)"
            )
        self._epoch = data["epoch"]
        values, expiry = self._state.values, self._state.expiry_ms
        locs = data["locs"]
        if locs:
            lsh = np.asarray([s for s, _ in locs], np.int32)
            lsl = np.asarray([sl for _, sl in locs], np.int32)
            values = values.at[lsh, lsl].set(np.asarray(data["lvalues"]))
            expiry = expiry.at[lsh, lsl].set(np.asarray(data["lexpiry"]))
        gslots = np.asarray(data["gslots"], np.int32)
        if gslots.size:
            values = values.at[:, gslots].set(np.asarray(data["gvalues"]))
            expiry = expiry.at[:, gslots].set(np.asarray(data["gexpiry"]))
        # The hit accumulator is telemetry, not state: restores count
        # afresh from the constructor's zeros.
        self._state = ShardedCounterState(values, expiry, self._state.hits)
        for table, dump in zip(self._tables, data["tables"]):
            table.load(dump, self._global_region, self._local_capacity)
        self._gtable.load(data["gtable"], 0, self._global_region)
        seed: List[Tuple[int, int, int]] = []
        for key, (value, exp, counter) in data.get("big", {}).items():
            key = _migrate_key(key)
            cell = restore_cell(counter.limit, value, exp)
            if isinstance(cell, GcraValue) and not self._is_big(counter):
                # Routing migration, same as TpuStorage._apply_snapshot:
                # pre-r4 checkpoints kept device-eligible buckets in the
                # big map; seed the owner shard's TAT cell instead of
                # orphaning the state. Device-eligible buckets are never
                # global (_is_big forces global-ns buckets host-side),
                # so the returned shard is always concrete.
                shard, slot, _fresh, _is_global = self._slot_for(
                    counter, create=True
                )
                seed.append((shard, slot, min(
                    max(int(cell.tat) - int(self._epoch * 1000), 0),
                    _INT32_MAX,
                )))
                continue
            self._big[key] = (cell, counter)
        if seed:
            sh = np.asarray([s for s, _, _ in seed], np.int32)
            sl = np.asarray([s for _, s, _ in seed], np.int32)
            tat = np.asarray([t for _, _, t in seed], np.int32)
            self._state = ShardedCounterState(
                self._state.values.at[sh, sl].set(0),
                self._state.expiry_ms.at[sh, sl].set(tat),
                self._state.hits,
            )
        return self

    def close(self) -> None:
        pass


# -- slice-granular checkpoint decode (elastic pod, ISSUE 15) ------------------


def snapshot_manifest(path: str) -> dict:
    """The shard-ownership manifest of a sharded checkpoint, WITHOUT
    building a storage: which global shard block the writing host owned
    and under which topology. Pre-ISSUE-15 checkpoints (no manifest)
    return an empty ``manifest`` — the caller falls back to the legacy
    ``.host<id>`` interpretation."""
    import pickle

    with open(path, "rb") as f:
        data = pickle.load(f)
    return {
        "format": data.get("format"),
        "n_shards": data.get("n_shards"),
        "manifest": dict(data.get("manifest") or {}),
    }


def _decoded_value(counter, value: int, expiry_ms: int, now_rel: int,
                   ) -> int:
    """One device cell's host-visible spend at ``now_rel`` (ms since the
    checkpoint's epoch): fixed windows read the values lane gated on
    expiry; bucket cells derive spent tokens from the TAT lane (the
    values lane is unspecified for buckets — same rule as read_slots)."""
    if counter.limit.policy == "token_bucket":
        base_rel = max(int(expiry_ms) - now_rel, 0)
        return spent_tokens(
            counter.max_value, counter.limit.seconds, base_rel
        )
    if int(expiry_ms) <= now_rel:
        return 0
    return int(value)


def snapshot_items(path: str, clock=time.time):
    """Decode a sharded checkpoint into live ``(counter, spend)`` items
    host-side — the slice-granular restore lane (ISSUE 15): after a
    membership change the owned shard ranges no longer match any single
    checkpoint file, so a restarting host decodes every sibling
    checkpoint and seeds ONLY the counters it owns under the current
    topology through the storage's ``apply_deltas`` contract (fresh
    windows, exact spends — the same accuracy contract as a failover
    journal replay). Expired cells decode to nothing."""
    import pickle

    with open(path, "rb") as f:
        data = pickle.load(f)
    now = float(clock())
    now_rel = int((now - float(data["epoch"])) * 1000)
    items = []
    tables = [dict(d.get("info", {})) for d in data.get("tables", ())]
    lvalues = np.asarray(data.get("lvalues", ()))
    lexpiry = np.asarray(data.get("lexpiry", ()))
    for i, (shard, slot) in enumerate(data.get("locs", ())):
        entry = tables[shard].get(slot) if shard < len(tables) else None
        if entry is None:
            continue
        _key, counter = entry
        value = _decoded_value(
            counter, int(lvalues[i]), int(lexpiry[i]), now_rel
        )
        if value > 0:
            items.append((counter, value))
    # global region: the read-as-sum of every shard's partial
    ginfo = dict(data.get("gtable", {}).get("info", {}))
    gslots = np.asarray(data.get("gslots", ())).tolist()
    gvalues = np.asarray(data.get("gvalues", ()))
    gexpiry = np.asarray(data.get("gexpiry", ()))
    for j, slot in enumerate(gslots):
        entry = ginfo.get(int(slot))
        if entry is None:
            continue
        _key, counter = entry
        if counter.limit.policy == "token_bucket":
            continue  # _is_big keeps global-ns buckets host-side
        if gexpiry.size and int(gexpiry[:, j].max()) <= now_rel:
            continue
        value = int(gvalues[:, j].sum()) if gvalues.size else 0
        if value > 0:
            items.append((counter, value))
    # host-side big map (over-cap limits and host buckets)
    for _key, (a, b, counter) in data.get("big", {}).items():
        cell = restore_cell(counter.limit, a, b)
        value = int(cell.value_at(now))
        if value > 0:
            items.append((counter, value))
    return items
