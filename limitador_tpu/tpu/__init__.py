from .batcher import AsyncTpuStorage, MicroBatcher
from .sharded import TpuShardedStorage
from .storage import TpuStorage

__all__ = [
    "TpuStorage",
    "TpuShardedStorage",
    "AsyncTpuStorage",
    "MicroBatcher",
]
