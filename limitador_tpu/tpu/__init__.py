from .batcher import AsyncTpuStorage, MicroBatcher
from .storage import TpuStorage

__all__ = ["TpuStorage", "AsyncTpuStorage", "MicroBatcher"]
