"""Replicated TPU counter storage: device-resident counts gossiped across
nodes.

The multi-host topology the brief calls for: each node keeps ITS OWN hit
counts in the device table (exact local admission at device speed), while a
CRDT gossip layer — the same wire protocol / Broker as the host-memory
distributed mode (storage/distributed/broker.py) — replicates per-actor
counts between nodes over DCN. Admission sees

    value = own device count  +  sum of live remote actors' counts

which is exactly the read-as-sum of the reference's CRDT mode
(cr_counter_value.rs:38-46) with the local addend living in HBM. Remote
sums sit in a second device array folded into the admission base by the
shared kernel core's ``base_hook``; gossip merges per-actor by max (idempotent,
commutative) on the host and scatters refreshed sums to the device.

Consistency contract: local decisions are exact against (own + last gossiped
remote) counts; cross-node over-admission is bounded by the gossip period —
the reference's documented distributed-mode behavior (doc/topologies.md).

Counters of limits whose max_value exceeds the int32 device cap (2^30)
live in the host-side big-limit fallback (exact Python ints, no device
slot); their local counts gossip through the same broker stream and the
remote per-actor sums fold into host-side admission via the
``_big_remote_sum`` hook — the u64 scale of the reference's CRDT mode
(cr_counter_value.rs:34-46) without the device cap ever applying.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..core.counter import Counter
from ..storage.gcra import GcraValue, spent_tokens
from ..storage.keys import (
    LimitKeyIndex,
    key_for_counter,
    partial_counter_from_key,
)
from ..ops import kernel as K
from .storage import TpuStorage

__all__ = ["TpuReplicatedStorage"]

DEFAULT_GOSSIP_PERIOD = 0.1


@functools.partial(jax.jit, donate_argnums=(0,))
def _replicated_check(state, remote_vals, remote_exp, slots, deltas, maxes,
                      windows_ms, req_ids, fresh, bucket, now_ms):
    """check_and_update over the merged admission base; only the LOCAL
    cells are written. Fixed windows fold the gossiped remote SUM into
    the base (read-as-sum, cr_counter_value.rs:38-46); token buckets fold
    the gossiped remote TAT as a FLOOR on the local TAT (max-merge join —
    a shared TAT, not additive counts), which the kernel then persists
    into the local cell on admitted writes so subsequent gossip carries
    the join."""
    # Same sorted-order trick as the sharded base_hook (parallel/mesh.py):
    # hooks receive sorted hits, so sort the per-hit policy lane the same
    # way (XLA dedups the repeated stable argsort).
    order = K.jnp.argsort(slots, stable=True)
    s_bucket = bucket[order]

    def base_hook(v_local, s_slot):
        r = remote_vals[s_slot]
        live = now_ms < remote_exp[s_slot]
        # bucket lanes carry their remote share via tat_floor_hook
        return v_local + K.jnp.where(
            K.jnp.logical_or(s_bucket, ~live), 0, r
        )

    def tat_floor_hook(s_slot):
        # remote_exp holds the max-merged remote TAT for bucket slots
        # (epoch-relative ms, refreshed at gossip/flush time)
        return K.jnp.where(s_bucket, remote_exp[s_slot], 0)

    nv, ne, nh, admitted, ok, remaining, ttl = K.check_and_update_core(
        state.values, state.expiry_ms, slots, deltas, maxes, windows_ms,
        req_ids, fresh, bucket, now_ms, num_req=slots.shape[0],
        base_hook=base_hook, tat_floor_hook=tat_floor_hook,
        hits=state.hits,
    )
    return (
        K.CounterTableState(nv, ne, nh),
        K.BatchResult(admitted, ok, remaining, ttl),
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def _replicated_update(state, remote_exp, slots, deltas, windows_ms, fresh,
                       bucket, now_ms):
    """Unconditional updates over the merged bucket state: the gossiped
    remote TAT folds in as a floor on the local TAT before the advance,
    so the Report role persists the shared-bucket join exactly like the
    check path does (no briefly-under-counted window between a replayed
    update and the next admitted check). Fixed windows are untouched —
    remote window counts are additive, not a joinable lane."""
    order = K.jnp.argsort(slots, stable=True)
    s_bucket = bucket[order]

    def tat_floor_hook(s_slot):
        # remote_exp holds the max-merged remote TAT for bucket slots
        # (epoch-relative ms, refreshed at gossip/flush time)
        return K.jnp.where(s_bucket, remote_exp[s_slot], 0)

    nv, ne, nh = K.update_core(
        state.values, state.expiry_ms, slots, deltas, windows_ms, fresh,
        bucket, now_ms, tat_floor_hook=tat_floor_hook, hits=state.hits,
    )
    return K.CounterTableState(nv, ne, nh)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _apply_remote(remote_vals, remote_exp, slots, sums, expiries):
    return (
        remote_vals.at[slots].set(sums),
        remote_exp.at[slots].set(expiries),
    )


class TpuReplicatedStorage(TpuStorage):
    # Token buckets replicate as a SHARED TAT max-merged per actor (r5):
    # the TAT is monotone under both admission (max(TAT, now) + d*I) and
    # merge (join-semilattice max), exactly like the expiry merge of
    # cr_counter_value.rs:77-113, so gossip is idempotent/commutative/
    # associative. The wire reuses the (count, expires_at) pair: count
    # carries the TAT in the limit's ticks, expires_at the TAT in abs ms
    # (the liveness lane — a TAT in the past = full bucket = no state).
    # Local admission checks against max(local TAT, gossiped remote TAT)
    # and persists the join; cross-node over-admission is bounded by what
    # peers admit within one gossip period (concurrent spends collapse to
    # their max at merge), the same bounded-inaccuracy contract as the
    # fixed-window read-as-sum. The UNCONDITIONAL update path
    # (update_counter / apply_deltas — the Report role and redis_import
    # replay) folds the same remote floor via _kernel_update /
    # _replicated_update, so replayed traffic persists the shared-bucket
    # join instead of briefly under-counting until the next admitted
    # check or gossip merge (the divergence ADVICE r5 called out).
    supports_token_bucket = True

    def __init__(
        self,
        node_id: str,
        listen_address: Optional[str] = None,
        peers: Optional[List[str]] = None,
        capacity: int = 1 << 20,
        cache_size: Optional[int] = None,
        gossip_period: float = DEFAULT_GOSSIP_PERIOD,
        clock=time.time,
        advertise_address: Optional[str] = None,
    ):
        super().__init__(capacity=capacity, cache_size=cache_size, clock=clock)
        self.node_id = node_id
        self.gossip_period = gossip_period
        # device-side remote sums (slot-indexed, scratch row inert)
        self._remote_vals = K.jnp.zeros((capacity + 1,), K.jnp.int32)
        self._remote_exp = K.jnp.zeros((capacity + 1,), K.jnp.int32)
        # host-side per-actor remote state: key -> {actor: (count, exp_ms)}
        self._remote_actors: Dict[bytes, Dict[str, Tuple[int, int]]] = {}
        self._dirty_remote: Dict[int, Tuple[int, int]] = {}  # slot -> (sum, exp)
        self._touched: set = set()  # keys touched locally since last gossip
        # big-limit (host-side) cells: identity tuple <-> wire key, plus
        # the set touched locally since the last gossip tick
        self._big_wire: Dict[tuple, bytes] = {}
        self._touched_big: set = set()
        # wire keys whose limit wasn't configured when gossip arrived
        self._parked_wires: set = set()
        self.broker = None
        self._gossip_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if listen_address is not None:
            from ..storage.distributed.broker import Broker

            self.broker = Broker(
                peer_id=node_id,
                listen_address=listen_address,
                peer_urls=peers or [],
                on_update=self._on_remote_update,
                snapshot_provider=self._snapshot_for_peer,
                advertise_address=advertise_address,
            )
            self.broker.start()
            self._gossip_thread = threading.Thread(
                target=self._gossip_loop, daemon=True,
                name=f"tpu-gossip-{node_id}",
            )
            self._gossip_thread.start()

    # -- kernel dispatch with remote base ----------------------------------

    def _kernel_check(self, slots, deltas, maxes, windows, req, fresh,
                      bucket, now_ms):
        self._flush_dirty_remote()
        # one vectorized unique, not a python loop over H hits
        self._touched.update(
            int(s) for s in np.unique(slots) if s != self._scratch
        )
        state, result = _replicated_check(
            self._state, self._remote_vals, self._remote_exp,
            slots, deltas, maxes, windows, req, fresh, bucket, now_ms,
        )
        return state, result

    def _kernel_update(self, slots, deltas, windows, fresh, bucket, now_ms):
        # The unconditional path folds the gossiped remote TAT floor the
        # same way the check path does (shared-bucket join persists on
        # Report-role / replay traffic too).
        self._flush_dirty_remote()
        return _replicated_update(
            self._state, self._remote_exp, slots, deltas, windows, fresh,
            bucket, now_ms,
        )

    def _slot_for(self, counter: Counter, create: bool):
        slot, fresh = super()._slot_for(counter, create)
        if fresh and slot is not None:
            # Remote updates that arrived before this counter's limit was
            # configured locally parked in _remote_actors; adopt them now.
            # ALWAYS queued (also when this key has no remote state): a
            # recycled slot's remote lane may still carry the previous
            # occupant's live remote entry, which would otherwise fold
            # into the new counter's admission base.
            self._queue_remote_sum(
                key_for_counter(counter), slot, counter=counter
            )
        return slot, fresh

    def _clear_adopted_slot(self, slot: int) -> None:
        """Zero a freshly allocated slot's LOCAL device cell. Adoption
        paths (gossip/re-sync arriving for a counter this node never
        served) allocate without a following kernel batch, so the
        kernel's fresh-flag override never cleans a recycled slot —
        without this, every later read/batch sees the previous
        occupant's cell (r5 review finding). Caller holds the lock."""
        self._state = K.clear_slots(
            self._state, np.asarray([slot], np.int32)
        )

    def _queue_remote_sum(
        self, key: bytes, slot: int, counter: Optional[Counter] = None
    ) -> None:
        """Recompute the live remote share for a key and queue the device
        scatter. Fixed windows: (sum of live counts, max expiry). Token
        buckets: (0, max live remote TAT) — the TAT rides the expiry lane
        and folds in as the kernel's tat floor. Caller holds the lock."""
        actors = self._remote_actors.get(key, {})
        now_ms = self._now_ms()
        epoch_ms = self._epoch * 1000
        if counter is None:
            info = self._table.info.get(slot)
            counter = info[1] if info is not None else None
        is_bucket = (
            counter is not None
            and counter.limit.policy == "token_bucket"
        )
        # liveness: expires_at (windows) / TAT (buckets) still in the
        # future — an expired entry carries no state either way
        live = [(c, e) for c, e in actors.values() if e - epoch_ms > now_ms]
        if is_bucket:
            # device-eligible buckets tick in ms, so the gossiped tick
            # count and the abs-ms lane agree; merge is max
            tat_rel = max((int(e - epoch_ms) for _c, e in live), default=0)
            self._dirty_remote[slot] = (
                0, max(0, min(tat_rel, (1 << 31) - 1))
            )
            return
        total = sum(c for c, _e in live)
        exp_rel = max((int(e - epoch_ms) for _c, e in live), default=0)
        self._dirty_remote[slot] = (
            min(total, K.MAX_VALUE_CAP),
            max(0, min(exp_rel, (1 << 31) - 1)),
        )

    def _on_big_write(self, key: tuple) -> None:
        # Caller holds the lock (mixin contract); the gossip tick publishes.
        self._touched_big.add(key)

    def _wire_for(self, key: tuple, counter: Counter) -> bytes:
        """Identity-tuple -> wire-key mapping, filled on first use (the
        codec is deterministic, so a locally computed wire key equals the
        bytes a peer gossips for the same counter). Caller holds the
        lock."""
        wire = self._big_wire.get(key)
        if wire is None:
            wire = key_for_counter(counter)
            self._big_wire[key] = wire
        return wire

    def _big_cell(self, counter: Counter, key: tuple):
        cell = super()._big_cell(counter, key)
        # Mapping doubles as ADOPTION: per-actor state that gossiped in
        # before this limit was configured locally parked under the wire
        # key in _remote_actors and becomes visible to _big_remote now.
        self._wire_for(key, counter)
        return cell

    def _lift_big_bucket(self, key: tuple, cell: GcraValue) -> None:
        """Max-merge live remote TATs into the local host bucket cell —
        the shared-TAT join for beyond-device buckets. Peers gossip the
        TAT in the limit's own ticks (count lane) with the abs-ms TAT as
        the liveness lane; the join is idempotent so repeated lifts are
        free. Caller holds the lock."""
        wire = self._big_wire.get(key)
        actors = self._remote_actors.get(wire) if wire is not None else None
        if not actors:
            return
        now_abs_ms = self._clock() * 1000
        for tat_ticks, exp_ms in actors.values():
            if exp_ms > now_abs_ms and tat_ticks > cell.tat:
                cell.tat = int(tat_ticks)

    def _big_remote(self, key: tuple, now: float):
        """(live remote sum, max live expiry abs-ms), one actors pass.
        Bucket cells take the max-merge path instead: the remote share is
        folded INTO the cell (shared TAT), so their remote sum is 0."""
        entry = self._big.get(key)
        if entry is not None and isinstance(entry[0], GcraValue):
            self._lift_big_bucket(key, entry[0])
            return 0, 0
        wire = self._big_wire.get(key)
        actors = self._remote_actors.get(wire) if wire is not None else None
        if not actors:
            return 0, 0
        now_abs_ms = now * 1000
        total = 0
        max_exp = 0
        for count, exp in actors.values():
            if exp > now_abs_ms:
                total += count
                if exp > max_exp:
                    max_exp = exp
        return total, max_exp

    def _big_remote_sum(self, key: tuple, now: float) -> int:
        return self._big_remote(key, now)[0]

    def _adopt_parked(self) -> None:
        """Fold gossip that arrived before its limit was configured:
        decode parked wire keys; decodable big counters get a host cell
        (+ wire mapping), device counters a slot — so admission and the
        merged view see re-sync/gossip regardless of arrival order.
        Caller holds the lock."""
        for wire in list(self._parked_wires):
            counter = self._decode_counter(wire)
            if counter is None:
                continue
            self._parked_wires.discard(wire)
            if self._is_big(counter):
                key_t = self._key_of(counter)
                self._big_wire[key_t] = wire
                self._big_cell(counter, key_t)
            else:
                slot, fresh = self._slot_for(counter, create=True)
                if fresh:
                    self._clear_adopted_slot(slot)
                self._queue_remote_sum(wire, slot)

    def _emit_big_counters(self, limits, namespaces, now, out) -> None:
        """Merged (local + live remote) view of big counters, including
        remote-only ones whose local cell never fired."""
        self._adopt_parked()
        for key, (cell, counter) in list(self._big.items()):
            if not (
                counter.limit in limits or counter.namespace in namespaces
            ):
                continue
            local = 0 if cell.is_expired(now) else cell.value_at(now)
            remote, remote_exp = self._big_remote(key, now)
            if cell.is_expired(now) and remote <= 0:
                continue
            ttl = cell.ttl(now) if not cell.is_expired(now) else 0.0
            if remote_exp:
                ttl = max(ttl, remote_exp / 1000.0 - now)
            c = counter.key()
            c.remaining = c.max_value - local - remote
            c.expires_in = ttl
            out.add(c)

    def _delete_big(self, limits) -> None:
        with self._lock:
            doomed = [
                key
                for key, (_cell, counter) in self._big.items()
                if counter.limit in limits
            ]
            for key in doomed:
                # Drop the mapping and pending publish but KEEP the
                # per-actor remote state — the device delete path leaves
                # _remote_actors intact too, so a live peer's window is
                # re-adopted at the next local touch instead of being
                # over-admitted away (the mapping recomputes
                # deterministically via _wire_for).
                self._big_wire.pop(key, None)
                self._touched_big.discard(key)
        super()._delete_big(limits)

    def update_counter(self, counter: Counter, delta: int) -> None:
        super().update_counter(counter, delta)
        if self._is_big(counter):
            return  # _on_big_write already queued the gossip
        # unconditional updates bypass _kernel_check; still gossip them
        with self._lock:
            slot, _ = self._slot_for(counter, create=False)
            if slot is not None:
                self._touched.add(slot)

    def apply_deltas(self, items):
        # The batched Report path (UpdateBatcher) and write-behind
        # authorities land here; like update_counter, these increments
        # bypass _kernel_check and must still gossip.
        out = super().apply_deltas(items)
        with self._lock:
            for counter, _delta in items:
                if self._is_big(counter):
                    continue  # _on_big_write already queued the gossip
                slot, _ = self._slot_for(counter, create=False)
                if slot is not None:
                    self._touched.add(slot)
        return out

    def _now_ms(self) -> int:
        # The parent rebases the local table's epoch on long uptimes; the
        # remote arrays share that epoch and must shift identically.
        prev_epoch = self._epoch
        now = super()._now_ms()
        if self._epoch != prev_epoch:
            shift = int((self._epoch - prev_epoch) * 1000)
            self._remote_exp = K.jnp.maximum(self._remote_exp - shift, 0)
        return now

    def _flush_dirty_remote(self) -> None:
        if not self._dirty_remote:
            return
        items = list(self._dirty_remote.items())
        self._dirty_remote = {}
        slots = np.asarray([s for s, _ in items], np.int32)
        sums = np.asarray([v for _, (v, _e) in items], np.int32)
        exps = np.asarray([e for _, (_v, e) in items], np.int32)
        self._remote_vals, self._remote_exp = _apply_remote(
            self._remote_vals, self._remote_exp, slots, sums, exps
        )

    # -- reads include remote counts ----------------------------------------

    def _remote_value(self, slot: int, now_ms: int) -> int:
        self._flush_dirty_remote()
        r = int(np.asarray(self._remote_vals[slot]))
        e = int(np.asarray(self._remote_exp[slot]))
        return r if now_ms < e else 0

    def set_limits_provider(self, provider) -> None:
        """Wired by the Storage facade: lets wire-key decoding see limits
        configured locally before any counter touched them."""
        self._limits_provider = provider

    def is_within_limits(self, counter: Counter, delta: int) -> bool:
        if self._is_big(counter):
            # Host-side cell; the parent's big branch folds the gossiped
            # remote share via _big_remote_sum. Ensure parked gossip for
            # this counter is adopted first (the device branch's
            # `create = wire in _remote_actors` analogue).
            with self._lock:
                if key_for_counter(counter) in self._remote_actors:
                    self._big_cell(counter, self._key_of(counter))
            return super().is_within_limits(counter, delta)
        with self._lock:
            now_ms = self._now_ms()
            create = key_for_counter(counter) in self._remote_actors
            slot, fresh = self._slot_for(counter, create=create)
            if slot is None:
                return delta <= counter.max_value
            if fresh:
                self._clear_adopted_slot(slot)
            v, ttl = K.read_slots(
                self._state, np.asarray([slot], np.int32), np.int32(now_ms)
            )
            # A freshly allocated/recycled slot's device cell is the
            # PREVIOUS occupant's stale state — local reads are 0 until
            # the first write (the kernel's segment-freshness rule; the
            # remote lane was re-queued by _slot_for and flushes below).
            if counter.limit.policy == "token_bucket":
                # merged spent derives from the max of local and remote
                # TAT (read_slots' ttl lane is the local base_rel)
                self._flush_dirty_remote()
                r_rel = max(
                    int(np.asarray(self._remote_exp[slot])) - now_ms, 0
                )
                local_rel = 0 if fresh else int(np.asarray(ttl)[0])
                value = spent_tokens(
                    counter.max_value, counter.window_seconds,
                    max(local_rel, r_rel),
                )
            else:
                local_v = 0 if fresh else int(np.asarray(v)[0])
                value = local_v + self._remote_value(slot, now_ms)
        return value + delta <= counter.max_value

    def get_counters(self, limits):
        out = super().get_counters(limits)
        with self._lock:
            now_ms = self._now_ms()
            self._flush_dirty_remote()
            merged = []
            for c in out:
                qualified_slot = self._table.qualified.get(self._key_of(c))
                slot = (
                    qualified_slot
                    if qualified_slot is not None
                    else self._table.simple.get(self._key_of(c))
                )
                if slot is not None and c.remaining is not None:
                    merged.append((slot, c))
            if merged:
                # One batched gather for every local counter's remote share
                # (scalar _remote_value fetches would serialize 2 device
                # round trips per counter under the storage lock).
                slot_arr = np.asarray([s for s, _c in merged], np.int32)
                rvals = np.asarray(self._remote_vals[slot_arr])
                rexps = np.asarray(self._remote_exp[slot_arr])
                for i, (_slot, c) in enumerate(merged):
                    if int(rexps[i]) <= now_ms:
                        continue
                    if c.limit.policy == "token_bucket":
                        # shared TAT: merged spent is the max, not a sum
                        r_spent = spent_tokens(
                            c.max_value, c.window_seconds,
                            int(rexps[i]) - now_ms,
                        )
                        c.remaining = min(
                            c.remaining, c.max_value - r_spent
                        )
                        c.expires_in = max(
                            c.expires_in,
                            (int(rexps[i]) - now_ms) / 1000.0,
                        )
                    else:
                        c.remaining -= int(rvals[i])
            # Remote-only counters: gossiped from peers, never locally hit —
            # the local cell is expired so the base pass skipped them, but
            # the merged view must list them (the reference's distributed
            # get_counters reads the CRDT sum, distributed/mod.rs). One
            # batched device gather for all candidates, like the parent.
            seen = set(out)
            namespaces = {limit.namespace for limit in limits}
            candidates = []
            for slot, (_key, counter) in self._table.info.items():
                if (
                    counter.limit not in limits
                    and counter.namespace not in namespaces
                ):
                    continue
                probe = counter.key()
                if probe not in seen:
                    candidates.append((slot, probe))
            if candidates:
                slot_arr = np.asarray([s for s, _p in candidates], np.int32)
                rvals = np.asarray(self._remote_vals[slot_arr])
                rexps = np.asarray(self._remote_exp[slot_arr])
                for i, (_slot, probe) in enumerate(candidates):
                    r, e = int(rvals[i]), int(rexps[i])
                    if e <= now_ms:
                        continue
                    if probe.limit.policy == "token_bucket":
                        # remote-only bucket: spent derives from the
                        # gossiped TAT (the count lane is unused)
                        r = spent_tokens(
                            probe.max_value, probe.window_seconds,
                            e - now_ms,
                        )
                    if r <= 0:
                        continue
                    probe.remaining = probe.max_value - r
                    probe.expires_in = (e - now_ms) / 1000.0
                    out.add(probe)
        return out

    # -- gossip plumbing ----------------------------------------------------

    def _on_remote_update(
        self, key: bytes, values: Dict[str, int], expires_at_ms: int
    ) -> None:
        """Merge a peer's snapshot: per-actor max (idempotent), recompute the
        slot's remote sum, queue the device scatter."""
        now_abs_ms = self._clock() * 1000
        with self._lock:
            actors = self._remote_actors.setdefault(key, {})
            for actor, count in values.items():
                if actor == self.node_id:
                    continue
                old = actors.get(actor)
                if old is None or old[1] <= now_abs_ms:
                    # No live state (or the old window expired): adopt the
                    # incoming count wholesale — per-actor windows RESET on
                    # expiry (cr_counter_value.rs merge_at), max-merge only
                    # applies within a live window.
                    actors[actor] = (count, expires_at_ms)
                elif expires_at_ms > now_abs_ms:
                    actors[actor] = (
                        max(count, old[0]),
                        max(expires_at_ms, old[1]),
                    )
            # locate / allocate the slot for this counter
            counter = self._decode_counter(key)
            if counter is None:
                # Limit not configured here yet: the per-actor state stays
                # parked (tracked in _parked_wires) and is adopted lazily —
                # at first local touch or by _adopt_parked.
                self._parked_wires.add(key)
                return
            self._parked_wires.discard(key)
            if self._is_big(counter):
                # Host-side cell: ensure it exists so reads/emission see
                # the remote share; admission folds it via _big_remote_sum
                # (windows) or the TAT lift (buckets).
                key_t = self._key_of(counter)
                cell = self._big_cell(counter, key_t)
                self._big_wire[key_t] = key
                if isinstance(cell, GcraValue):
                    self._lift_big_bucket(key_t, cell)
                return
            slot, fresh = self._slot_for(counter, create=True)
            if fresh:
                self._clear_adopted_slot(slot)
            self._queue_remote_sum(key, slot)

    def _decode_counter(self, key: bytes) -> Optional[Counter]:
        # Counters decode against the configured limits (registry provider);
        # an unknown limit's updates park in _remote_actors until the limit
        # is configured here. The O(#slots) info scan is only the
        # providerless fallback (bare-storage tests). Gossip floods decode
        # one key per update, so the LimitKeyIndex is cached and only
        # rebuilt when the provider's limit set actually changes.
        try:
            limits = self._known_limits()
            if not limits:
                limits = {info[1].limit for info in self._table.info.values()}
            cached = self._decode_index
            if cached is None or cached[0] != limits:
                cached = (limits, LimitKeyIndex(limits))
                self._decode_index = cached
            return partial_counter_from_key(key, cached[1])
        except Exception:
            return None

    _limits_provider = None  # set by the server: () -> iterable of limits
    _decode_index = None  # (limits set, LimitKeyIndex) decode cache

    def _known_limits(self):
        if self._limits_provider is None:
            return set()
        try:
            return set(self._limits_provider())
        except Exception:
            return set()

    def _snapshot_for_peer(self):
        """Re-sync: ship our own live counts for every live local counter."""
        out = []
        with self._lock:
            now_ms = self._now_ms()
            values = np.asarray(self._state.values)
            expiry = np.asarray(self._state.expiry_ms)
            for slot, (_key, counter) in self._table.info.items():
                if expiry[slot] > now_ms:
                    # windows: expiry lane; buckets: the TAT — in both
                    # cases "still in the future" means live state
                    expires_at = int(
                        self._epoch * 1000 + int(expiry[slot])
                    )
                    if counter.limit.policy == "token_bucket":
                        payload = {self.node_id: expires_at}
                    else:
                        payload = {self.node_id: int(values[slot])}
                    out.append(
                        (key_for_counter(counter), payload, expires_at)
                    )
            now = self._clock()
            for key, (cell, counter) in self._big.items():
                if cell.is_expired(now):
                    continue
                wire = self._wire_for(key, counter)
                if isinstance(cell, GcraValue):
                    # host (beyond-device) buckets gossip TAT in their
                    # own ticks — scale derives deterministically from
                    # the limit, so peers agree on the unit
                    payload = {self.node_id: int(cell.tat)}
                else:
                    payload = {self.node_id: min(int(cell.value_at(now)),
                                                 (1 << 63) - 1)}
                out.append(
                    (
                        wire,
                        payload,
                        int(now * 1000 + cell.ttl(now) * 1000),
                    )
                )
        return out

    def _gossip_loop(self) -> None:
        ticks = 0
        while not self._stop.wait(self.gossip_period):
            self._publish_touched()
            ticks += 1
            if ticks % 100 == 0:
                self._prune_remote_actors()

    def _prune_remote_actors(self) -> None:
        """Drop expired per-actor entries and empty keys so long-running
        nodes with churning qualified counters don't grow host memory
        without bound."""
        now_abs_ms = self._clock() * 1000
        with self._lock:
            doomed_keys = []
            for key, actors in self._remote_actors.items():
                dead = [a for a, (_c, e) in actors.items() if e <= now_abs_ms]
                for a in dead:
                    del actors[a]
                if not actors:
                    doomed_keys.append(key)
            for key in doomed_keys:
                del self._remote_actors[key]
            self._parked_wires &= set(self._remote_actors)
            # A mapping is live while its cell exists or remote state does.
            self._big_wire = {
                k: w
                for k, w in self._big_wire.items()
                if k in self._big or w in self._remote_actors
            }

    def _publish_touched(self) -> None:
        if self.broker is None:
            return
        self._publish_touched_big()
        with self._lock:
            touched, self._touched = self._touched, set()
            if not touched:
                return
            now_ms = self._now_ms()
            slots = []
            wire_keys = []
            buckets = []
            for slot in touched:
                info = self._table.info.get(slot)
                if info is not None:
                    slots.append(slot)
                    wire_keys.append(key_for_counter(info[1]))
                    buckets.append(
                        info[1].limit.policy == "token_bucket"
                    )
            if not slots:
                return
            v, ttl = K.read_slots(
                self._state, np.asarray(slots, np.int32), np.int32(now_ms)
            )
            v = np.asarray(v)
            ttl = np.asarray(ttl)
            epoch_ms = self._epoch * 1000
        for i, key in enumerate(wire_keys):
            if ttl[i] <= 0:
                # expired window / full bucket: nothing live to gossip
                continue
            expires_at = int(epoch_ms + now_ms + int(ttl[i]))
            if buckets[i]:
                # bucket state IS the TAT: for device-eligible buckets the
                # ttl lane is base_rel = TAT - now, ticks are ms, so the
                # count lane carries the same abs-ms TAT as expires_at
                payload = {self.node_id: expires_at}
            else:
                payload = {self.node_id: int(v[i])}
            self.broker.publish(key, payload, expires_at)

    def _publish_touched_big(self) -> None:
        """Gossip locally-written big cells: exact Python-int counts on
        the same wire stream (the proto carries u64; a count past that is
        clamped — it exceeds any expressible max_value anyway)."""
        to_send = []
        with self._lock:
            touched, self._touched_big = self._touched_big, set()
            now = self._clock()
            for key in touched:
                entry = self._big.get(key)
                if entry is None:
                    continue
                cell, counter = entry
                if cell.is_expired(now):
                    continue
                wire = self._wire_for(key, counter)
                expires_at = int(now * 1000 + cell.ttl(now) * 1000)
                if isinstance(cell, GcraValue):
                    # bucket state IS the TAT (limit-derived ticks)
                    count = int(cell.tat)
                else:
                    count = min(int(cell.value_at(now)), (1 << 63) - 1)
                to_send.append((wire, count, expires_at))
        for wire, count, expires_at in to_send:
            self.broker.publish(wire, {self.node_id: count}, expires_at)

    # -- lifecycle -----------------------------------------------------------

    def clear(self) -> None:
        super().clear()
        with self._lock:
            self._remote_vals = K.jnp.zeros_like(self._remote_vals)
            self._remote_exp = K.jnp.zeros_like(self._remote_exp)
            self._remote_actors.clear()
            self._dirty_remote.clear()
            self._touched.clear()
            self._big_wire.clear()
            self._touched_big.clear()
            self._parked_wires.clear()

    def close(self) -> None:
        self._stop.set()
        if self._gossip_thread is not None:
            self._gossip_thread.join(timeout=2)
        if self.broker is not None:
            self.broker.stop()
