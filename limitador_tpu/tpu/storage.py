"""TpuStorage — the device-resident counter backend.

Implements the ``CounterStorage`` protocol (storage/base.py, mirroring
/root/reference/limitador/src/storage/mod.rs:279-293) over the fused kernel
in limitador_tpu/ops/kernel.py. Equivalent of the reference's
``InMemoryStorage`` in exactness (never over-admits; check-all-then-
update-all) with counters living in device HBM instead of host maps:

- The host owns the key space: counter identity -> slot index, mirroring the
  reference's split between the unbounded simple-limits map
  (in_memory.rs:14) and the LRU-capped qualified-counter cache
  (in_memory.rs:15-16, 204-212). Qualified slots are evicted LRU (as moka's
  cap does); simple-limit slots are pinned.
- The device owns the values: a dense int32 (value, expiry_ms) table; every
  check/update is a fused gather -> admit -> scatter kernel call.
- ``check_many`` is the single implementation of hit-array construction,
  reference processing order, first-limited naming and the non-load
  early-return slot-release semantics; the per-call ``check_and_update``
  and the async MicroBatcher (tpu/batcher.py) both go through it.

Documented representation limits (see ops/kernel.py): max_value clamps to
2**30, deltas to 2**30-1, windows to WINDOW_MS_CAP (~12.4 days). The epoch
auto-rebases on long uptimes so expiries never overflow int32.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core.counter import Counter
from ..core.limit import Limit
from ..storage.base import (
    Authorization,
    CounterStorage,
    StorageError,
    require_nonnegative_delta,
)
from ..storage.expiring_value import ExpiringValue
from ..storage.gcra import (
    GcraValue,
    cell_for_limit,
    device_eligible,
    emission_interval_ms,
    restore_cell,
    spent_tokens,
)
from ..ops import kernel as K

__all__ = ["TpuStorage"]

_INT32_MAX = np.int32(np.iinfo(np.int32).max)


def _bucket(n: int, floor: int = 8) -> int:
    """Next power of two >= n (static kernel shapes, few XLA programs)."""
    b = floor
    while b < n:
        b <<= 1
    return b


def _clamp_window_ms(seconds: int) -> int:
    return min(seconds * 1000, K.WINDOW_MS_CAP)


def _staged(values, H: int, fill, dtype) -> np.ndarray:
    """Right-sized staging array: prefix from a Python list (one C-level
    conversion), padding filled with the inert default — replaces the
    ``np.asarray(list + [pad] * k)`` pattern that built a second
    H-element Python list per column per batch."""
    arr = np.empty(H, dtype)
    n = len(values)
    if n:
        arr[:n] = values
    if n < H:
        arr[n:] = fill
    return arr


def _native_partition(group_ids: np.ndarray, n_groups: int):
    """Native (GIL-free, O(n), no argsort) grouped cumcount when the
    hostpath library is ALREADY loaded — never triggers a first-use
    compile from a staging pass. Returns None to keep the numpy path."""
    try:
        from .. import native
    except Exception:  # pragma: no cover - import cycles in odd embeddings
        return None
    try:
        return native.partition_positions(group_ids, n_groups)
    except Exception:
        return None


def _partition_positions(
    group_ids: np.ndarray, n_groups: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized grouped cumcount: for flat staged rows labeled with a
    group (a shard id, a request's home shard), return
    ``(counts[n_groups], pos)`` where ``pos[i]`` is row i's index WITHIN
    its group, counted in input order. This is the host side of the
    sharded partition step, riding every MicroBatcher flush on sharded
    storage. Two implementations, identical outputs: the native one
    (one O(n) C pass, hostpath.cc ``hp_partition_positions``) when the
    library is already loaded, else one argsort + two cumsums — either
    way no per-row Python (tests/test_perf_smoke.py budgets it)."""
    m = group_ids.shape[0]
    if m >= 2048:
        native_out = _native_partition(group_ids, n_groups)
        if native_out is not None:
            return native_out
    counts = np.bincount(group_ids, minlength=n_groups)
    order = np.argsort(group_ids, kind="stable")
    starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
    pos = np.empty(m, np.int64)
    pos[order] = np.arange(m, dtype=np.int64) - np.repeat(starts, counts)
    return counts, pos


def _scatter_rows(
    shard_ids: np.ndarray,
    pos: np.ndarray,
    n: int,
    H: int,
    columns: Sequence[Tuple[Sequence, object, type]],
) -> List[np.ndarray]:
    """Scatter flat hit columns into per-shard ``[n, H]`` staging arrays
    (``(values, fill, dtype)`` per column) — one fancy-index store per
    column, pad rows pre-filled with the inert default. Flat order is
    request order, and ``pos`` counts per shard in flat order, so each
    shard's rows stay in request order (the kernel's nondecreasing
    req_ids contract)."""
    out = []
    for values, fill, dtype in columns:
        arr = np.full((n, H), fill, dtype)
        arr[shard_ids, pos] = values
        out.append(arr)
    return out


def hot_attribution(counter: Counter, value: int, ttl_ms: int) -> dict:
    """Tenant-usage attribution fields for one drained heavy-hitter slot
    (ISSUE 8): full slot->counter identity plus the utilization sample
    read at drain time. Shared by the single-chip and sharded drains.
    ``value`` is the raw values-lane read; bucket counters derive spent
    tokens from the ttl lane instead (their values lane is
    unspecified)."""
    limit = counter.limit
    if limit.policy == "token_bucket":
        value = spent_tokens(
            counter.max_value, counter.window_seconds, ttl_ms
        )
    max_value = int(counter.max_value)
    util = value / max_value if max_value > 0 else 0.0
    return {
        "namespace": str(counter.namespace),
        "limit_name": limit.name,
        "policy": limit.policy,
        "max_value": max_value,
        "seconds": counter.window_seconds,
        "key": dict(counter.set_variables),
        "value": int(value),
        # Unclamped on purpose: >1.0 is real signal (Report-role
        # unconditional updates can push past max_value).
        "utilization": round(util, 4),
        "ttl_s": round(ttl_ms / 1000.0, 3),
    }


def _hit_lane(counter: Counter) -> Tuple[int, bool]:
    """Per-hit (windows_ms lane, bucket flag) for a device-eligible
    counter: the window for fixed windows, the GCRA emission interval
    for token buckets (ops/kernel.py bucket lane)."""
    limit = counter.limit
    if limit.policy == "token_bucket":
        return emission_interval_ms(limit.max_value, limit.seconds), True
    return _clamp_window_ms(counter.window_seconds), False


def _migrate_key(key):
    """Pre-policy checkpoints: limit identity was a 4-tuple
    (ns, seconds, conditions, variables); current lookups build 5-tuples
    ending in the policy. Old keys are fixed-window."""
    if (
        isinstance(key, tuple) and len(key) == 2
        and isinstance(key[0], tuple) and len(key[0]) == 4
    ):
        return (key[0] + ("fixed_window",), key[1])
    return key


class _SlotTable:
    """Host-side key space: counter identity -> device slot."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.free: List[int] = list(range(capacity - 1, -1, -1))
        # pinned (simple-limit) slots: key -> slot
        self.simple: Dict[tuple, int] = {}
        # LRU for qualified counters: key -> slot (front = oldest)
        self.qualified: "OrderedDict[tuple, int]" = OrderedDict()
        # slot -> (key, Counter identity object) for introspection
        self.info: Dict[int, Tuple[tuple, Counter]] = {}
        # slot -> native composite key + removal hook (native fast path)
        self.native_keys: Dict[int, object] = {}
        self.on_native_release = None
        # Decision-plan cache coherence (tpu/plan_cache.py): every slot
        # release fires on_slot_release(slot) so cached plans pinning the
        # slot are dropped before it can be recycled; wholesale table
        # swaps (clear/snapshot-restore) fire on_clear instead.
        self.on_slot_release = None
        self.on_clear = None
        # Device-plane telemetry (device_stats()): cumulative counts of
        # LRU evictions and of fresh allocations that recycled a
        # previously-occupied slot (the kernel's fresh flag overrides the
        # stale cell). Host bookkeeping only — never reset by dump/load.
        self.evictions = 0
        self.collisions = 0
        self._recycled: set = set()

    def lookup(self, key: tuple, qualified: bool) -> Optional[int]:
        if qualified:
            slot = self.qualified.get(key)
            if slot is not None:
                self.qualified.move_to_end(key)
            return slot
        return self.simple.get(key)

    def dump(self) -> dict:
        """Checkpoint form. The free list is NOT persisted (it would be
        O(capacity)); ``load`` derives it from the occupied set."""
        return {
            "simple": dict(self.simple),
            "qualified": list(self.qualified.items()),
            "info": dict(self.info),
        }

    def load(self, data: dict, lo: int, hi: int) -> None:
        """Restore from ``dump`` output; slots of this table live in
        [lo, hi)."""
        self.simple = {
            _migrate_key(k): v for k, v in dict(data["simple"]).items()
        }
        self.qualified.update(
            (_migrate_key(k), v) for k, v in data["qualified"]
        )
        self.info = {
            s: (_migrate_key(key), counter)
            for s, (key, counter) in dict(data["info"]).items()
        }
        if "free" in data:  # older checkpoints persisted the free list
            self.free = list(data["free"])
        else:
            occupied = set(self.info)
            self.free = [
                s for s in range(hi - 1, lo - 1, -1) if s not in occupied
            ]

    def alloc(self) -> int:
        """Pop a free slot; counts the recycled-slot collision when the
        slot held a (now released) counter before. Callers guarantee
        ``free`` is non-empty."""
        slot = self.free.pop()
        if slot in self._recycled:
            self._recycled.discard(slot)
            self.collisions += 1
        return slot

    def release(self, slot: int, key: tuple, qualified: bool) -> None:
        self.info.pop(slot, None)
        if qualified:
            self.qualified.pop(key, None)
        else:
            self.simple.pop(key, None)
        self.free.append(slot)
        self._recycled.add(slot)
        # Eviction coherence with the native slot map: a recycled slot must
        # not remain reachable under its old native key.
        native_key = self.native_keys.pop(slot, None)
        if native_key is not None and self.on_native_release is not None:
            self.on_native_release(native_key)
        if self.on_slot_release is not None:
            self.on_slot_release(slot)


class _BigLimitMixin:
    """Host-side exact counters for limits whose max_value exceeds the
    int32 device cap (the reference's max_value is u64, limit.rs:34).
    Shared by the single-chip and sharded storages; every method assumes
    the caller holds the storage lock.

    Admission projection (``_big_inflight``) spans in-flight batches: a
    hit admitted at begin time reserves its delta immediately, so a
    second pipelined batch can never over-admit against a stale value;
    the reservation is released (and the delta actually applied when the
    whole request was admitted) at finish."""

    def _init_big(self, cap: int) -> None:
        self._big: "OrderedDict[tuple, Tuple[ExpiringValue, Counter]]" = (
            OrderedDict()
        )
        self._big_inflight: Dict[tuple, int] = {}
        self._big_cap = max(int(cap), 1)
        # Per-limit routing memos: is-big and the (window_ms, bucket)
        # hit lane are pure functions of (limit identity, max_value),
        # re-derived on every hit before — the two getattr/compare
        # chains profiled in the host_stage phase. max_value is NOT part
        # of Limit identity (an update_limit may change only it), so it
        # rides in the key explicitly. Bounded: pruned wholesale past a
        # cap (limits registries are small; churn only comes from
        # reload loops).
        self._big_flags: Dict[tuple, bool] = {}
        self._lanes: Dict[tuple, Tuple[int, bool]] = {}

    def _is_big(self, counter: Counter) -> bool:
        # Token buckets run ON DEVICE (a TAT cell in the expiry lane,
        # ops/kernel.py bucket lane) whenever the int32-ms representation
        # fits; only finer-tick / beyond-cap buckets ride the exact host
        # path, same as beyond-cap fixed windows.
        limit = counter.limit
        key = (limit, limit.max_value)
        flag = self._big_flags.get(key)
        if flag is None:
            if limit.policy == "token_bucket":
                flag = not device_eligible(
                    counter.max_value, counter.window_seconds,
                    K.MAX_VALUE_CAP, K.WINDOW_MS_CAP,
                )
            else:
                flag = counter.max_value > K.MAX_VALUE_CAP
            if len(self._big_flags) >= 4096:
                self._big_flags.clear()
            self._big_flags[key] = flag
        return flag

    def _lane_of(self, counter: Counter) -> Tuple[int, bool]:
        """Memoized ``_hit_lane`` — per-(limit, max_value), not
        per-hit."""
        limit = counter.limit
        key = (limit, limit.max_value)
        lane = self._lanes.get(key)
        if lane is None:
            lane = _hit_lane(counter)
            if len(self._lanes) >= 4096:
                self._lanes.clear()
            self._lanes[key] = lane
        return lane

    def _big_cell(self, counter: Counter, key: tuple) -> ExpiringValue:
        entry = self._big.get(key)
        if entry is not None:
            self._big.move_to_end(key)
            return entry[0]
        cell = cell_for_limit(counter.limit)
        self._big[key] = (cell, counter.key())
        while len(self._big) > self._big_cap:
            evicted = False
            for k in self._big:
                if k != key and k not in self._big_inflight:
                    del self._big[k]
                    evicted = True
                    break
            if not evicted:
                break
        return cell

    def _big_remote_sum(self, key: tuple, now: float) -> int:
        """Live remote contribution to a big cell's admission base —
        0 here; the replicated topology overrides it with the gossiped
        per-actor sum (tpu/replicated.py)."""
        return 0

    def _on_big_write(self, key: tuple) -> None:
        """Hook: a big cell was locally incremented (caller holds the
        lock). The replicated topology queues it for gossip."""

    def _eval_big_hits(self, ordered, raw_delta: int, now: float):
        """First pass of a request: decide its big hits host-side.
        Returns (bigs, failed, projected) where each big is
        (j, ok, remaining, ttl_s, key, counter, delta) and projected lists
        (key, delta) reservations to release at finish."""
        bigs: list = []
        projected: List[Tuple[tuple, int]] = []
        failed = False
        for j, c in enumerate(ordered):
            if not self._is_big(c):
                continue
            key = self._key_of(c)
            cell = self._big_cell(c, key)
            value = (
                cell.value_at(now)
                + self._big_inflight.get(key, 0)
                + self._big_remote_sum(key, now)
            )
            ok = value + raw_delta <= c.max_value
            remaining = max(c.max_value - (value + raw_delta), 0)
            if isinstance(cell, GcraValue):
                # Token bucket: expires_in is time-to-full (0 = full);
                # there is no "fresh window" display case.
                ttl = cell.ttl(now)
            else:
                ttl = (
                    float(c.window_seconds)
                    if cell.is_expired(now) else cell.ttl(now)
                )
            bigs.append((j, ok, remaining, ttl, key, c, raw_delta))
            if ok:
                self._big_inflight[key] = (
                    self._big_inflight.get(key, 0) + raw_delta
                )
                projected.append((key, raw_delta))
            else:
                failed = True
        return bigs, failed, projected

    def _unproject_big(self, projected) -> None:
        for key, delta in projected:
            cur = self._big_inflight.get(key, 0) - delta
            if cur > 0:
                self._big_inflight[key] = cur
            else:
                self._big_inflight.pop(key, None)

    def _apply_big(self, applies, now: float) -> None:
        for key, delta, window in applies:
            entry = self._big.get(key)
            if entry is not None:
                entry[0].update(delta, window, now)
                self._on_big_write(key)

    def _emit_big_counters(self, limits, namespaces, now: float, out) -> None:
        for _key, (cell, counter) in self._big.items():
            if (
                counter.limit in limits
                or counter.namespace in namespaces
            ) and not cell.is_expired(now):
                c = counter.key()
                c.remaining = c.max_value - cell.value_at(now)
                c.expires_in = cell.ttl(now)
                out.add(c)

    def _delete_big(self, limits) -> None:
        for key, (_cell, counter) in list(self._big.items()):
            if counter.limit in limits:
                del self._big[key]

    def _clear_big(self) -> None:
        self._big.clear()
        self._big_inflight.clear()


class _Request:
    """One logical check inside a ``check_many`` batch."""

    __slots__ = ("ordered", "delta", "load")

    def __init__(self, counters: Sequence[Counter], delta: int, load: bool):
        # Reference processing order: simple counters then qualified
        # (in_memory.rs:104-139) — drives first_limited naming.
        self.ordered = [c for c in counters if not c.is_qualified()] + [
            c for c in counters if c.is_qualified()
        ]
        self.delta = delta
        self.load = load


class _CheckHandle:
    """In-flight batch: kernel launched, results not yet transferred.
    Produced by ``begin_check_many``, consumed by ``finish_check_many`` —
    the split lets the batcher dispatch batch N+1 while N's device->host
    transfer is still in flight (double buffering)."""

    __slots__ = ("requests", "fresh_hits_by_req", "slot_use_count",
                 "result", "seq", "watch_touches", "big_by_req",
                 "dev_info_by_req", "now", "big_projected")

    def __init__(self, requests, fresh_hits_by_req, slot_use_count, result,
                 seq, watch_touches, big_by_req, dev_info_by_req, now,
                 big_projected=()):
        self.requests = requests
        self.fresh_hits_by_req = fresh_hits_by_req
        self.slot_use_count = slot_use_count
        self.result = result
        self.seq = seq
        # Every slot whose _watched_slots entry this batch wrote; the
        # finish pass deletes the ones still carrying this batch's seq so
        # the watch map stays bounded by in-flight work.
        self.watch_touches = watch_touches
        # Host-side (max_value > device cap) hits, per request:
        # (j, ok, remaining, ttl_s, key, counter, delta).
        self.big_by_req = big_by_req
        # Device hits per request: (j, delta_adjust) in device-array order.
        self.dev_info_by_req = dev_info_by_req
        self.now = now
        # (key, delta) reservations in _big_inflight, released at finish.
        self.big_projected = big_projected


class TpuStorage(_BigLimitMixin, CounterStorage):
    supports_token_bucket = True  # via the exact host (big-limit) path

    def __init__(
        self,
        capacity: int = 1 << 20,
        cache_size: Optional[int] = None,
        clock=time.time,
    ):
        """``capacity`` sizes the device table (8 bytes/counter of HBM);
        ``cache_size`` caps qualified counters (default: capacity)."""
        self._lock = threading.RLock()
        self._clock = clock
        self._capacity = int(capacity)
        self._cache_size = int(cache_size) if cache_size else self._capacity
        self._table = _SlotTable(self._capacity)
        self._state = K.make_table(self._capacity)
        self._epoch = clock()  # device time 0 in host seconds
        self._scratch = self._capacity  # padding slot
        # Pipelining bookkeeping: batch sequence number + last-touch seq of
        # slots watched for deferred release (see finish_check_many).
        self._seq = 0
        self._watched_slots: Dict[int, int] = {}
        # Host-side fallback for limits whose max_value exceeds the int32
        # device cap: these counters never get a device slot (see
        # _BigLimitMixin); LRU-capped like the device's qualified cache.
        self._init_big(self._cache_size)

    # -- time --------------------------------------------------------------

    def _now_ms(self) -> int:
        now = int((self._clock() - self._epoch) * 1000)
        if now > (1 << 30):
            # Rebase before now_ms + WINDOW_MS_CAP could overflow int32.
            shift = now - 1000
            self._state = K.CounterTableState(
                self._state.values,
                K.rebase_epoch_chunked(self._state.expiry_ms, shift),
                self._state.hits,
            )
            self._epoch += shift / 1000.0
            now -= shift
        return now

    # -- slot management ---------------------------------------------------

    @staticmethod
    def _key_of(counter: Counter) -> tuple:
        # Counter._key() memoizes the identity tuple on the counter, so
        # reused counter objects (the compiled path's plan cache) stop
        # paying per-hit tuple construction + re-hash.
        return counter._key()

    def _evict_one(self) -> None:
        """Free the least-recently-used qualified slot (the moka cap
        analogue, in_memory.rs:204-212). Pure host bookkeeping: the recycled
        slot's stale device cell is overridden by the kernel's ``fresh``
        flag on next allocation — no device read or write here."""
        if not self._table.qualified:
            raise StorageError("TPU counter table full (no evictable slots)")
        key, slot = next(iter(self._table.qualified.items()))
        self._table.release(slot, key, qualified=True)
        self._table.evictions += 1

    def _slot_for(self, counter: Counter, create: bool) -> Tuple[Optional[int], bool]:
        """Return (slot, fresh). fresh=True when allocated/recycled now."""
        qualified = counter.is_qualified()
        key = self._key_of(counter)
        slot = self._table.lookup(key, qualified)
        if slot is not None:
            return slot, False
        if not create:
            return None, False
        if qualified:
            while len(self._table.qualified) >= self._cache_size:
                self._evict_one()
        if not self._table.free:
            self._evict_one()
        slot = self._table.alloc()
        if qualified:
            self._table.qualified[key] = slot
        else:
            self._table.simple[key] = slot
        self._table.info[slot] = (key, counter.key())
        return slot, True

    def device_stats(self) -> dict:
        """Device-plane table stats for /debug/stats and the per-shard
        Prometheus gauges (observability/device_plane.py): occupancy as a
        level, evictions/collisions as cumulative counts."""
        with self._lock:
            t = self._table
            return {
                "shards": [{
                    "shard": "0",
                    "occupied": len(t.info),
                    "capacity": t.capacity,
                    "evictions": t.evictions,
                    "collisions": t.collisions,
                }],
            }

    def drain_hot_slots(self, k: int = 64) -> List[dict]:
        """Heavy-hitter drain (ISSUE 8 tenant usage observatory):
        read-and-reset the per-slot hit accumulator and attribute the K
        hottest slots through the slot table — namespace, limit, key
        values, hit count, plus a value/max_value utilization sample and
        ttl read at drain time. One donated top-k kernel + one
        ``read_slots`` gather, entirely OFF the check path (the
        accumulator itself rides the existing check/update scatters —
        zero extra launches there, perf-smoke enforced). Attribution is
        resolved at drain: a slot recycled within one drain interval
        attributes its counts to the current occupant (or drops them
        when the slot is free) — bounded by the drain period, and only
        under table eviction pressure."""
        with self._lock:
            hits = self._state.hits
            if hits is None or k <= 0:
                return []
            now_ms = self._now_ms()
            new_hits, counts, slots = K.drain_top_hits(
                hits, min(int(k), self._capacity)
            )
            self._state = K.CounterTableState(
                self._state.values, self._state.expiry_ms, new_hits
            )
            counts = np.asarray(counts)
            slots = np.asarray(slots)
            live = counts > 0
            if not live.any():
                return []
            slots = slots[live].astype(np.int32)
            counts = counts[live]
            values, ttls = K.read_slots(
                self._state, slots, np.int32(now_ms)
            )
            values = np.asarray(values)
            ttls = np.asarray(ttls)
            out: List[dict] = []
            info = self._table.info
            for i, slot in enumerate(slots.tolist()):
                record = {"slot": int(slot), "count": int(counts[i])}
                entry = info.get(slot)
                if entry is not None:
                    record.update(hot_attribution(
                        entry[1], int(values[i]), int(ttls[i])
                    ))
                out.append(record)
            return out

    def attribute_slots(self, slot_counts: Dict[int, int]) -> List[dict]:
        """Attribution records for externally-counted slot traffic —
        the native lane's leased admissions never reach the device
        accumulator, so the usage observatory counts them C-side and
        resolves them here: same record shape as ``drain_hot_slots``,
        counts supplied by the caller. Slots whose counter has been
        released since the counts were taken are dropped (their debit
        died with the cell)."""
        if not slot_counts:
            return []
        with self._lock:
            now_ms = self._now_ms()
            info = self._table.info
            items = [
                (slot, count) for slot, count in slot_counts.items()
                if slot in info
            ]
            if not items:
                return []
            slots = np.asarray([s for s, _ in items], np.int32)
            values, ttls = K.read_slots(
                self._state, slots, np.int32(now_ms)
            )
            values = np.asarray(values)
            ttls = np.asarray(ttls)
            out: List[dict] = []
            for i, (slot, count) in enumerate(items):
                record = {
                    "slot": int(slot), "count": int(count),
                    "source": "lease",
                }
                record.update(hot_attribution(
                    info[slot][1], int(values[i]), int(ttls[i])
                ))
                out.append(record)
            return out

    # -- the shared batched check path -------------------------------------

    def _kernel_check(self, slots, deltas, maxes, windows, req, fresh,
                      bucket, now_ms):
        """Kernel dispatch point; the replicated subclass swaps in a kernel
        that folds remote (gossiped) counts into the admission base."""
        return K.check_and_update_batch(
            self._state, slots, deltas, maxes, windows, req, fresh, bucket,
            now_ms,
        )

    def _kernel_update(self, slots, deltas, windows, fresh, bucket, now_ms):
        """Unconditional-update dispatch point (update_counter /
        apply_deltas); the replicated subclass swaps in a kernel that
        folds the gossiped remote TAT floor into bucket advances, so
        Report-role traffic cannot briefly under-count shared buckets."""
        return K.update_batch(
            self._state, slots, deltas, windows, fresh, bucket, now_ms,
        )

    def begin_check_many(self, requests: List[_Request]) -> _CheckHandle:
        """Build hit arrays and launch the kernel WITHOUT waiting for the
        device->host transfer. Table mutations are serialized under the
        lock in call order, which is also device program order, so batch
        N+1 may begin while N's results are still in flight.

        Counters whose max_value exceeds the device cap are decided
        host-side here (exact Python ints): a failing big hit strips the
        request's device deltas before the launch, so admission stays
        all-or-nothing; passing big hits apply at finish only when the
        device also admits (projected within the batch so concurrent big
        hits never over-admit)."""
        for request in requests:
            require_nonnegative_delta(request.delta)
        # Build as Python lists (then one vectorized pad+convert): per-element
        # numpy scalar stores dominate the host loop otherwise.
        slots_l: List[int] = []
        deltas_l: List[int] = []
        maxes_l: List[int] = []
        windows_l: List[int] = []
        req_l: List[int] = []
        fresh_l: List[bool] = []
        bucket_l: List[bool] = []

        with self._lock:
            now_ms = self._now_ms()
            now = self._clock()
            self._seq += 1
            seq = self._seq
            watched = self._watched_slots
            fresh_hits_by_req: List[List[Tuple[int, Counter, int]]] = []
            big_by_req: List[list] = []
            dev_info_by_req: List[List[Tuple[int, int]]] = []
            big_projected: List[Tuple[tuple, int]] = []
            watch_touches: List[int] = []
            slot_use_count: Dict[int, int] = {}
            slot_for = self._slot_for
            for r, request in enumerate(requests):
                fresh_hits: List[Tuple[int, Counter, int]] = []
                dev_info: List[Tuple[int, int]] = []
                raw_delta = int(request.delta)
                delta = min(raw_delta, K.MAX_DELTA_CAP)
                bigs, big_failed, projected = self._eval_big_hits(
                    request.ordered, raw_delta, now
                )
                big_projected.extend(projected)
                dev_delta = 0 if big_failed else delta
                adjust = delta if big_failed else 0
                for j, c in enumerate(request.ordered):
                    if self._is_big(c):
                        continue
                    slot, is_fresh = slot_for(c, create=True)
                    win, is_bucket = self._lane_of(c)
                    slots_l.append(slot)
                    deltas_l.append(dev_delta)
                    maxes_l.append(min(c.max_value, K.MAX_VALUE_CAP))
                    windows_l.append(win)
                    req_l.append(r)
                    fresh_l.append(is_fresh)
                    bucket_l.append(is_bucket)
                    slot_use_count[slot] = slot_use_count.get(slot, 0) + 1
                    dev_info.append((j, adjust))
                    if is_fresh:
                        fresh_hits.append((j, c, slot))
                        watch_touches.append(slot)
                        watched[slot] = seq
                    elif slot in watched:
                        # A later batch re-used a slot an earlier in-flight
                        # batch may want to release: the re-use wins.
                        watched[slot] = seq
                        watch_touches.append(slot)
                fresh_hits_by_req.append(fresh_hits)
                big_by_req.append(bigs)
                dev_info_by_req.append(dev_info)

            nhits = len(slots_l)
            H = _bucket(max(nhits, len(requests), 1))
            # One C-level conversion per column into a right-sized array
            # (no Python-level pad-list concatenation per batch).
            slots = _staged(slots_l, H, self._scratch, np.int32)
            deltas = _staged(deltas_l, H, 0, np.int32)
            maxes = _staged(maxes_l, H, int(_INT32_MAX), np.int32)
            windows = _staged(windows_l, H, 0, np.int32)
            req = _staged(req_l, H, H - 1, np.int32)
            fresh = _staged(fresh_l, H, False, bool)
            bucket = _staged(bucket_l, H, False, bool)

            self._state, result = self._kernel_check(
                slots, deltas, maxes, windows, req, fresh, bucket,
                np.int32(now_ms),
            )
        return _CheckHandle(
            requests, fresh_hits_by_req, slot_use_count, result, seq,
            watch_touches, big_by_req, dev_info_by_req, now, big_projected,
        )

    def finish_check_many(self, handle: _CheckHandle) -> List[Authorization]:
        """Transfer and decode one in-flight batch: load_counters side
        effects, first-limited naming, and the reference's non-load
        early-return semantics (a limited non-load request does not create
        qualified counters past its first limited hit, in_memory.rs:110-133
        — only safe to undo when no other request in the batch shares the
        freshly-allocated slot and no later batch has re-used it)."""
        import jax

        result = handle.result
        try:
            # One transfer for all three outputs (matters over remote links).
            hit_ok, remaining, ttl_ms = jax.device_get(
                (result.hit_ok, result.remaining, result.ttl_ms)
            )
        except BaseException:
            # The projection reservations must not leak when the transfer
            # fails, else those big counters under-admit forever — and
            # neither may the watch entries: a stale seq would suppress
            # every later batch's release of these slots.
            with self._lock:
                self._unproject_big(handle.big_projected)
                watched = self._watched_slots
                for slot in handle.watch_touches:
                    if watched.get(slot) == handle.seq:
                        del watched[slot]
            raise

        auths: List[Authorization] = []
        releases: List[Tuple[Counter, int]] = []
        big_applies: List[Tuple[tuple, int, int]] = []  # key, delta, window
        base = 0
        for r, request in enumerate(handle.requests):
            dev_info = handle.dev_info_by_req[r]
            bigs = handle.big_by_req[r]
            n_dev = len(dev_info)
            oks_by_j: Dict[int, bool] = {}
            for i, (j, _adjust) in enumerate(dev_info):
                oks_by_j[j] = bool(hit_ok[base + i])
            for j, ok, _rem, _ttl, _key, _c, _delta in bigs:
                oks_by_j[j] = ok
            all_ok = all(oks_by_j.values())
            if request.load:
                for i, (j, adjust) in enumerate(dev_info):
                    c = request.ordered[j]
                    c.remaining = max(int(remaining[base + i]) - adjust, 0)
                    c.expires_in = float(ttl_ms[base + i]) / 1000.0
                for j, _ok, rem, ttl, _key, _c, _delta in bigs:
                    c = request.ordered[j]
                    c.remaining = rem
                    c.expires_in = ttl
            if all_ok:
                auths.append(Authorization.OK)
                for _j, _ok, _rem, _ttl, key, c, delta in bigs:
                    big_applies.append((key, delta, c.window_seconds))
            else:
                first = min(j for j, ok in oks_by_j.items() if not ok)
                auths.append(
                    Authorization.limited_by(
                        request.ordered[first].limit.name
                    )
                )
                if not request.load:
                    for j, c, slot in handle.fresh_hits_by_req[r]:
                        if j > first and handle.slot_use_count.get(slot) == 1:
                            releases.append((c, slot))
            base += n_dev
        with self._lock:
            self._unproject_big(handle.big_projected)
            self._apply_big(big_applies, handle.now)
            watched = self._watched_slots
            for c, slot in releases:
                if watched.get(slot) != handle.seq:
                    continue
                # The table must still map this key to this slot — an
                # intervening delete/evict/clear means the slot was already
                # freed (releasing again would double-free it).
                key = self._key_of(c)
                qualified = c.is_qualified()
                mapped = (
                    self._table.qualified.get(key) == slot
                    if qualified
                    else self._table.simple.get(key) == slot
                )
                if mapped:
                    self._table.release(slot, key, qualified)
            for slot in handle.watch_touches:
                if watched.get(slot) == handle.seq:
                    del watched[slot]
        return auths

    def check_many(self, requests: List[_Request]) -> List[Authorization]:
        """Run a batch of check-all-then-update-all requests in one kernel
        launch, in list order (== serial order for exactness)."""
        return self.finish_check_many(self.begin_check_many(requests))

    # -- CounterStorage ----------------------------------------------------

    def is_within_limits(self, counter: Counter, delta: int) -> bool:
        with self._lock:
            now_ms = self._now_ms()
            if self._is_big(counter):
                key = self._key_of(counter)
                entry = self._big.get(key)
                value = (
                    entry[0].value_at(self._clock())
                    if entry is not None else 0
                ) + self._big_remote_sum(key, self._clock())
                return value + delta <= counter.max_value
            slot, _ = self._slot_for(counter, create=False)
            if slot is None:
                value = 0
            else:
                v, ttl = K.read_slots(
                    self._state, np.asarray([slot], np.int32), np.int32(now_ms)
                )
                if counter.limit.policy == "token_bucket":
                    # Bucket cells: the ttl lane is base_rel = max(TAT-now,
                    # 0); spent tokens derive from it (values lane is
                    # unspecified for buckets).
                    value = spent_tokens(
                        counter.max_value, counter.window_seconds, int(ttl[0])
                    )
                else:
                    value = int(v[0])
        return value + delta <= counter.max_value

    def add_counter(self, limit: Limit) -> None:
        if not limit.variables:
            with self._lock:
                counter = Counter(limit, {})
                if self._is_big(counter):
                    self._big_cell(counter, self._key_of(counter))
                else:
                    slot, fresh = self._slot_for(counter, create=True)
                    if fresh:
                        # No kernel batch follows this allocation, so the
                        # kernel's fresh-flag override can't clean a
                        # recycled slot — clear the cell now or the next
                        # (non-fresh) read/batch sees the old occupant.
                        self._state = K.clear_slots(
                            self._state, np.asarray([slot], np.int32)
                        )

    def update_counter(self, counter: Counter, delta: int) -> None:
        require_nonnegative_delta(delta)
        with self._lock:
            now_ms = self._now_ms()
            if self._is_big(counter):
                key = self._key_of(counter)
                cell = self._big_cell(counter, key)
                cell.update(int(delta), counter.window_seconds, self._clock())
                self._on_big_write(key)
                return
            slot, is_fresh = self._slot_for(counter, create=True)
            H = _bucket(1)
            slots = np.full(H, self._scratch, np.int32)
            deltas = np.zeros(H, np.int32)
            windows = np.zeros(H, np.int32)
            fresh = np.zeros(H, bool)
            bucket = np.zeros(H, bool)
            win, is_bucket = self._lane_of(counter)
            slots[0] = slot
            deltas[0] = min(int(delta), K.MAX_DELTA_CAP)
            windows[0] = win
            fresh[0] = is_fresh
            bucket[0] = is_bucket
            self._state = self._kernel_update(
                slots, deltas, windows, fresh, bucket, np.int32(now_ms)
            )

    def check_and_update(
        self, counters: List[Counter], delta: int, load_counters: bool
    ) -> Authorization:
        if not counters:
            return Authorization.OK
        return self.check_many([_Request(counters, delta, load_counters)])[0]

    # -- columnar entry point (native serving path) ------------------------

    def check_columnar(
        self,
        slots: np.ndarray,
        deltas: np.ndarray,
        maxes: np.ndarray,
        windows_ms: np.ndarray,
        req_ids: np.ndarray,
        fresh: np.ndarray,
        bucket: Optional[np.ndarray] = None,
    ):
        """Run one kernel over pre-built, request-ordered hit arrays (no
        per-hit Python objects). Caller pads to a bucket (use
        ``pad_hits``); returns host arrays (admitted, hit_ok, remaining,
        ttl_ms)."""
        return self.finish_check_columnar(
            self.begin_check_columnar(
                slots, deltas, maxes, windows_ms, req_ids, fresh, bucket
            )
        )

    def begin_check_columnar(
        self,
        slots: np.ndarray,
        deltas: np.ndarray,
        maxes: np.ndarray,
        windows_ms: np.ndarray,
        req_ids: np.ndarray,
        fresh: np.ndarray,
        bucket: Optional[np.ndarray] = None,
    ):
        """Launch the columnar kernel and return the in-flight device
        result (JAX async dispatch: this does not block on the device).
        ``finish_check_columnar`` collects it. Launches are ordered by
        the storage lock; the state array threads through launches, so a
        later begin is correct even while earlier results are still in
        flight — this is what lets a caller overlap batch N's device
        round trip with batch N+1's host work.

        ``bucket`` marks GCRA hits (``windows_ms`` then carries the
        emission interval); None means all fixed-window."""
        if bucket is None:
            bucket = np.zeros(slots.shape, bool)
        with self._lock:
            now_ms = self._now_ms()
            self._state, result = K.check_and_update_batch(
                self._state, slots, deltas, maxes, windows_ms, req_ids,
                fresh, bucket, np.int32(now_ms),
            )
            return result

    def finish_check_columnar(self, result, with_remaining: bool = True):
        """Block on a begin_check_columnar launch; returns host arrays
        (admitted, hit_ok, remaining, ttl_ms). ``with_remaining=False``
        transfers only the decision arrays (remaining/ttl come back as
        None) — on a high-RTT link the device->host copy is the round
        trip, so callers that don't load counters halve it."""
        import jax

        if not with_remaining:
            admitted, hit_ok = jax.device_get(
                (result.admitted, result.hit_ok)
            )
            return admitted, hit_ok, None, None
        return jax.device_get(
            (result.admitted, result.hit_ok, result.remaining,
             result.ttl_ms)
        )

    def credit_columnar(
        self,
        slots: np.ndarray,
        credits: np.ndarray,
        windows_ms: np.ndarray,
        bucket: np.ndarray,
    ) -> None:
        """Return unused leased quota to the device table (the lease
        broker's credit lane, lease/broker.py): one scatter kernel,
        floored so a credit can never create more headroom than a fresh
        cell. ``slots`` must be unique (callers aggregate per slot) and
        LIVE — the caller verifies slot->counter identity under this
        same lock, because a recycled slot's credit would land on a
        different counter. Rows are padded to the kernel's pow2 buckets
        with inert scratch writes (no per-length XLA program churn)."""
        n = int(slots.shape[0])
        if n == 0:
            return
        H = _bucket(n)
        with self._lock:
            now_ms = self._now_ms()
            self._state = K.credit_batch(
                self._state,
                _staged(slots, H, self._scratch, np.int32),
                _staged(credits, H, 0, np.int32),
                _staged(windows_ms, H, 0, np.int32),
                _staged(bucket, H, False, bool),
                np.int32(now_ms),
            )

    def pad_hits(self, arrays: Tuple[np.ndarray, ...], nhits: int):
        """Pad (slots, deltas, maxes, windows, req_ids, fresh[, bucket])
        to the next bucket with inert scratch hits."""
        H = _bucket(max(nhits, 1))
        slots, deltas, maxes, windows, req, fresh = arrays[:6]
        padded = (
            _staged(slots, H, self._scratch, np.int32),
            _staged(deltas, H, 0, np.int32),
            _staged(maxes, H, int(_INT32_MAX), np.int32),
            _staged(windows, H, 0, np.int32),
            _staged(req, H, H - 1, np.int32),
            _staged(fresh, H, False, bool),
        )
        if len(arrays) > 6:
            padded += (_staged(arrays[6], H, False, bool),)
        return padded

    # -- tier migration primitives (tier/storage.py) -----------------------

    def peek_slots(self, slots) -> Tuple[np.ndarray, np.ndarray]:
        """(value, ttl_ms) host arrays for ``slots`` at the current
        clock — the read half of an exact demotion. Caller holds the
        lock: the read must be atomic with the residency change it
        feeds. Padded to the kernel's pow2 buckets so migration-batch
        peeks of any size reuse a handful of compiled read programs."""
        n = len(slots)
        H = _bucket(n)
        now_ms = self._now_ms()
        values, ttls = K.read_slots(
            self._state,
            _staged(np.asarray(slots, np.int32), H, self._scratch, np.int32),
            np.int32(now_ms),
        )
        return np.asarray(values)[:n], np.asarray(ttls)[:n]

    def seed_slot_values(self, slots, values, expiry_rel_ms) -> None:
        """Absolute cell write for ``slots`` (tier promotion): value and
        epoch-relative expiry land verbatim (ops/kernel.py seed_slots),
        preserving the counter's exact remaining window — the update
        lane's ``fresh`` flag would restart it. Caller holds the lock;
        rows are padded to the pow2 bucket with inert scratch writes."""
        n = len(slots)
        if n == 0:
            return
        H = _bucket(n)
        self._state = K.seed_slots(
            self._state,
            _staged(np.asarray(slots, np.int32), H, self._scratch, np.int32),
            _staged(np.asarray(values, np.int32), H, 0, np.int32),
            _staged(np.asarray(expiry_rel_ms, np.int32), H, 0, np.int32),
        )

    def get_counters(self, limits: Set[Limit]) -> Set[Counter]:
        out: Set[Counter] = set()
        with self._lock:
            now_ms = self._now_ms()
            now = self._clock()
            namespaces = {limit.namespace for limit in limits}
            # Gather ONLY the matching live slots — O(matching counters)
            # transferred, not O(capacity) (the reference iterates a
            # namespace prefix the same way, rocksdb_storage.rs:91-130).
            matching: List[Tuple[int, Counter]] = [
                (slot, counter)
                for slot, (_key, counter) in self._table.info.items()
                if counter.limit in limits or counter.namespace in namespaces
            ]
            if matching:
                slot_arr = np.asarray([s for s, _c in matching], np.int32)
                values, ttls = K.read_slots(
                    self._state, slot_arr, np.int32(now_ms)
                )
                values = np.asarray(values)
                ttls = np.asarray(ttls)
                for i, (_slot, counter) in enumerate(matching):
                    ttl_ms = int(ttls[i])
                    if ttl_ms <= 0:
                        # fixed window expired / bucket full: no live state
                        continue
                    c = counter.key()
                    if c.limit.policy == "token_bucket":
                        c.remaining = c.max_value - spent_tokens(
                            c.max_value, c.window_seconds, ttl_ms
                        )
                    else:
                        c.remaining = c.max_value - int(values[i])
                    c.expires_in = ttl_ms / 1000.0
                    out.add(c)
            self._emit_big_counters(limits, namespaces, now, out)
        return out

    def delete_counters(self, limits: Set[Limit]) -> None:
        with self._lock:
            doomed: List[int] = []
            for slot, (key, counter) in list(self._table.info.items()):
                if counter.limit in limits:
                    doomed.append(slot)
                    self._table.release(slot, key, counter.is_qualified())
            if doomed:
                self._state = K.clear_slots(
                    self._state, np.asarray(doomed, np.int32)
                )
            self._delete_big(limits)

    def _replace_table(self) -> "_SlotTable":
        """Swap in a fresh slot table, carrying the coherence hooks over
        and firing the wholesale invalidation (every previously-issued
        slot index is dead). Caller holds the lock."""
        old = self._table
        self._table = _SlotTable(self._capacity)
        self._table.on_native_release = old.on_native_release
        self._table.on_slot_release = old.on_slot_release
        self._table.on_clear = old.on_clear
        if old.on_clear is not None:
            old.on_clear()
        return self._table

    def clear(self) -> None:
        with self._lock:
            self._replace_table()
            self._state = K.make_table(self._capacity)
            self._watched_slots.clear()
            self._clear_big()

    def apply_deltas(self, items):
        """Authority-side batch apply for write-behind caches: one
        update_batch + one read, vectorized (the device table playing the
        shared-Redis role of the reference's cached topology)."""
        for _counter, delta in items:
            require_nonnegative_delta(delta)
        with self._lock:
            now_ms = self._now_ms()
            now = self._clock()
            dev_items: List[Tuple[int, Counter, int]] = []
            results: List[Optional[Tuple[int, float]]] = [None] * len(items)
            for i, (counter, delta) in enumerate(items):
                if self._is_big(counter):
                    key = self._key_of(counter)
                    cell = self._big_cell(counter, key)
                    value = cell.update(
                        int(delta), counter.window_seconds, now
                    )
                    self._on_big_write(key)
                    results[i] = (value, cell.ttl(now))
                else:
                    dev_items.append((i, counter, delta))
            if dev_items:
                n = len(dev_items)
                H = _bucket(n)
                slots = np.full(H, self._scratch, np.int32)
                deltas = np.zeros(H, np.int32)
                windows = np.zeros(H, np.int32)
                fresh = np.zeros(H, bool)
                bucket = np.zeros(H, bool)
                for k, (_i, counter, delta) in enumerate(dev_items):
                    slot, is_fresh = self._slot_for(counter, create=True)
                    win, is_bucket = self._lane_of(counter)
                    slots[k] = slot
                    deltas[k] = min(int(delta), K.MAX_DELTA_CAP)
                    windows[k] = win
                    fresh[k] = is_fresh
                    bucket[k] = is_bucket
                self._state = self._kernel_update(
                    slots, deltas, windows, fresh, bucket, np.int32(now_ms)
                )
                values, ttls = K.read_slots(
                    self._state, slots[:n], np.int32(now_ms)
                )
                values = np.asarray(values)
                ttls = np.asarray(ttls)
                for k, (i, counter, _delta) in enumerate(dev_items):
                    if bucket[k]:
                        value = spent_tokens(
                            counter.max_value, counter.window_seconds,
                            int(ttls[k]),
                        )
                    else:
                        value = int(values[k])
                    results[i] = (value, float(ttls[k]) / 1000.0)
        return results

    # -- checkpoint / resume (SURVEY.md §5) ---------------------------------

    def snapshot(self, path: str) -> None:
        """Persist the counter state (device cells + host key space) so a
        restart resumes counting — the reopen semantics the reference gets
        from RocksDB (rocksdb_storage.rs:237-287), for the device table.

        Sparse: only occupied slots are transferred and written, so the
        checkpoint costs O(live counters), not O(capacity)."""
        import pickle

        with self._lock:
            occupied = np.asarray(sorted(self._table.info), np.int32)
            if occupied.size:
                # Device-side gather: only the occupied cells cross the
                # host link, not the whole table.
                values = np.asarray(self._state.values[occupied])
                expiry = np.asarray(self._state.expiry_ms[occupied])
            else:
                values = np.zeros(0, np.int32)
                expiry = np.zeros(0, np.int32)
            table = {
                "capacity": self._capacity,
                "cache_size": self._cache_size,
                "epoch": self._epoch,
                **self._table.dump(),
                "big": {
                    key: (
                        (cell.tat, cell.scale, counter)
                        if isinstance(cell, GcraValue)
                        else (cell.value_raw, cell.expiry, counter)
                    )
                    for key, (cell, counter) in self._big.items()
                },
            }
        with open(path, "wb") as f:
            pickle.dump(
                {
                    "format": 2,
                    "slots": occupied,
                    "values": values,
                    "expiry": expiry,
                    "table": table,
                },
                f,
            )

    def _apply_snapshot(self, data: dict) -> None:
        """Load checkpoint contents into THIS storage (caller holds no
        lock; capacities already verified to match)."""
        table = data["table"]
        with self._lock:
            # Keep the saved epoch so absolute expiries stay correct;
            # _now_ms rebases on its own schedule afterwards.
            self._epoch = table["epoch"]
            if data.get("format", 1) >= 2:
                slots = np.asarray(data["slots"], np.int32)
                if slots.size:
                    self._state = K.CounterTableState(
                        values=self._state.values.at[slots].set(
                            K.jnp.asarray(data["values"])
                        ),
                        expiry_ms=self._state.expiry_ms.at[slots].set(
                            K.jnp.asarray(data["expiry"])
                        ),
                        # telemetry, not state: checkpoints never carry
                        # the hit accumulator — restarts count afresh
                        hits=self._state.hits,
                    )
            else:  # round-1 dense checkpoints
                self._state = K.CounterTableState(
                    values=K.jnp.asarray(data["values"]),
                    expiry_ms=K.jnp.asarray(data["expiry"]),
                    hits=self._state.hits,
                )
            self._replace_table()
            self._table.load(table, 0, self._capacity)
            seed_slots: List[int] = []
            seed_tats: List[int] = []
            for key, (value, expiry, counter) in table.get("big", {}).items():
                # Same pre-policy key migration as _SlotTable.load: old
                # checkpoints hold 4-tuple limit identities.
                key = _migrate_key(key)
                cell = restore_cell(counter.limit, value, expiry)
                if isinstance(cell, GcraValue) and not self._is_big(counter):
                    # Routing migration: pre-r4 checkpoints kept EVERY
                    # token bucket in the big host map; device-eligible
                    # buckets now live in the device table. Seed the
                    # device TAT cell from the saved state — leaving the
                    # entry in _big would orphan it (never consulted →
                    # bucket silently resets to full) while
                    # _emit_big_counters kept emitting the stale cell.
                    slot, _fresh = self._slot_for(counter, create=True)
                    seed_slots.append(slot)
                    # GcraValue.tat is absolute ms (scale 1 when device
                    # eligible); the device lane is relative to _epoch.
                    # TAT <= now means "full bucket", same as 0.
                    seed_tats.append(min(
                        max(int(cell.tat) - int(self._epoch * 1000), 0),
                        int(_INT32_MAX),
                    ))
                    continue
                self._big[key] = (cell, counter)
            if seed_slots:
                idx = np.asarray(seed_slots, np.int32)
                self._state = K.CounterTableState(
                    values=self._state.values.at[idx].set(0),
                    expiry_ms=self._state.expiry_ms.at[idx].set(
                        np.asarray(seed_tats, np.int32)
                    ),
                    hits=self._state.hits,
                )

    def load_snapshot(self, path: str) -> None:
        """Restore a checkpoint into an already-constructed storage (the
        replicated subclass restores this way: its constructor owns the
        broker wiring, then state loads in)."""
        import pickle

        with open(path, "rb") as f:
            data = pickle.load(f)
        capacity = data["table"]["capacity"]
        if capacity != self._capacity:
            raise StorageError(
                f"snapshot capacity {capacity} != storage capacity "
                f"{self._capacity} (slot indices would shift)"
            )
        self._apply_snapshot(data)

    @classmethod
    def restore(
        cls, path: str, cache_size=None, clock=time.time
    ) -> "TpuStorage":
        """``cache_size`` may be overridden; capacity is fixed by the
        checkpoint (slot indices would shift otherwise)."""
        import pickle

        with open(path, "rb") as f:
            data = pickle.load(f)
        table = data["table"]
        self = cls(
            capacity=table["capacity"],
            cache_size=cache_size or table["cache_size"],
            clock=clock,
        )
        self._apply_snapshot(data)
        return self

    def close(self) -> None:
        pass
