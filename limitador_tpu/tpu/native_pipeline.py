"""Native columnar RLS serving path.

The fastest end-to-end route through the framework: the gRPC handler gives
this pipeline RAW serialized RateLimitRequest bytes (identity deserializer
— Python protobuf never runs on the hot path); a micro-batch of blobs then
flows

    C++ parse + intern -> token columns          (native/hostpath.cc)
    -> compiled predicate masks (numpy)          (tpu/compiler.py)
    -> composite-key slot lookup (C++ hash map)  (native slot map)
    -> ONE fused device kernel                   (ops/kernel.py)
    -> per-request OK / OVER_LIMIT blobs (prebuilt bytes)

Python objects only materialize off the fast path: slot-map misses
(allocation via the storage's key space, kept coherent with native keys so
LRU eviction invalidates both sides), requests with multiple descriptors,
namespaces with non-vectorizable limits, and header-loading modes — all of
which route to the exact per-request pipeline.

Semantics are the same exact check-all-then-update-all as everywhere else;
this module only changes how fast the batch is assembled.
"""

from __future__ import annotations

import asyncio
import contextvars
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.counter import Counter
from ..core.limit import Namespace
from ..observability.device_plane import current_request_id
from ..observability.metrics import PrometheusMetrics
from ..observability.tracing import device_batch_span
from ..storage.base import StorageError
from .. import native
from ..ops import kernel as K
from ..storage.gcra import device_eligible, emission_interval_ms
from .compiler import NamespaceCompiler
from .pipeline import CompiledTpuLimiter
from .storage import TpuStorage

__all__ = ["NativeRlsPipeline"]


class _NsPlan:
    """Per-namespace compiled plan bound to the native interner."""

    __slots__ = ("namespace", "compiler", "limits_meta")

    def __init__(self, namespace: Namespace, compiler: NamespaceCompiler, hp):
        self.namespace = namespace
        self.compiler = compiler
        # per vectorized limit: (limit_token, max, window_s, name, limit).
        # The token is interned from the limit's stable identity — compile
        # order must NOT leak into native slot keys, or a limits reload that
        # reorders limits would alias counters (plans rebuild, the native
        # slot map does not).
        self.limits_meta = [
            (
                hp.intern("limit\x00" + repr(cl.limit._identity)),
                cl.limit.max_value,
                cl.limit.window_seconds,
                cl.limit.name,
                cl.limit,
            )
            for cl in compiler.limits
        ]


class NativeRlsPipeline:
    """Owns the native context and decides batches of raw RLS blobs.

    ``submit(blob)`` resolves to the serialized RateLimitResponse bytes.
    """

    OK_BLOB: bytes
    OVER_BLOB: bytes
    UNKNOWN_BLOB: bytes
    #: decide_many marker for rows whose counter allocation failed
    #: (transient storage error; answer UNAVAILABLE)
    STORAGE_ERROR: object

    def __init__(
        self,
        limiter: CompiledTpuLimiter,
        metrics: Optional[PrometheusMetrics] = None,
        max_delay: float = 0.0005,
        max_batch: int = 8192,
        max_inflight: int = 2,
    ):
        if not native.available():
            raise RuntimeError(
                f"native hostpath unavailable: {native.build_error()}"
            )
        from ..server.proto import rls_pb2

        self._pb = rls_pb2
        self.OK_BLOB = rls_pb2.RateLimitResponse(
            overall_code=rls_pb2.RateLimitResponse.OK
        ).SerializeToString()
        self.OVER_BLOB = rls_pb2.RateLimitResponse(
            overall_code=rls_pb2.RateLimitResponse.OVER_LIMIT
        ).SerializeToString()
        self.UNKNOWN_BLOB = rls_pb2.RateLimitResponse(
            overall_code=rls_pb2.RateLimitResponse.UNKNOWN
        ).SerializeToString()

        self.limiter = limiter
        self.storage: TpuStorage = limiter._tpu.inner
        self.metrics = metrics
        if metrics is not None and metrics.custom_label_names:
            import sys as _sys

            print(
                "warning: --metric-labels values are not evaluated on the "
                "native columnar path; custom labels will be empty for "
                "requests it serves (use --pipeline compiled for per-request "
                "label values)",
                file=_sys.stderr,
            )
        self.max_delay = max_delay
        self.max_batch = max_batch
        #: concurrent dispatched-but-uncollected batches; 2 is enough to
        #: keep the device busy while the host parses the next batch.
        self.max_inflight = max_inflight

        self.hp = native.HostPath()
        self._interner = self.hp.as_interner()
        self._tracked: Dict[str, int] = {}
        self._plans: Dict[int, Optional[_NsPlan]] = {}  # domain token -> plan
        # (blob, future, enqueue time, request id) per pending request.
        self._pending: List[Tuple[bytes, asyncio.Future, float, object]] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._recorder = None  # memoized from the limiter on first sight
        self._flush_task: Optional[asyncio.Task] = None
        # Dispatch serializes host phases (the C++ context and the slot
        # path are single-threaded by design); collects may overlap.
        self._dispatch_pool = ThreadPoolExecutor(
            1, thread_name_prefix="native-dispatch"
        )
        self._collect_pool = ThreadPoolExecutor(
            max_inflight, thread_name_prefix="native-collect"
        )
        self._inflight: set = set()
        self._inflight_sem: Optional[asyncio.Semaphore] = None
        # seq -> dispatched-but-uncollected batch (for breaker-trip
        # draining, the MicroBatcher._inflight_batches pattern).
        self._inflight_batches: Dict[int, list] = {}
        self._batch_seq = 0
        # The C++ context is single-threaded by design; overlapping flushes
        # (timer + max_batch trigger) serialize here.
        self._native_lock = threading.Lock()
        #: rebuild the native context when the interner exceeds this many
        #: distinct strings (high-cardinality values must not grow RSS
        #: without bound; device counters are keyed by the Python table, so
        #: a rebuild only costs re-warming the caches).
        self.max_interned = 4 << 20
        # eviction coherence: python slot release -> native map removal
        self.storage._table.on_native_release = self.hp.slots_remove

    @property
    def recorder(self):
        """Device-plane telemetry sink, shared with the compiled limiter
        (set_metrics on the limiter wires it — possibly after this
        pipeline is constructed; one flight recorder and one batch-id
        sequence per process). Memoized on first sight so the per-request
        gate in submit() costs an attribute read, not a getattr chain."""
        rec = self._recorder
        if rec is None:
            rec = getattr(self.limiter, "recorder", None)
            if rec is not None:
                self._recorder = rec
        return rec

    # -- plan management ----------------------------------------------------

    def invalidate(self) -> None:
        """Limits changed: drop all plans (rebuilt lazily)."""
        self._plans.clear()

    def _plan_for(self, domain_token: int) -> Optional[_NsPlan]:
        plan = self._plans.get(domain_token, _MISSING_PLAN)
        if plan is not _MISSING_PLAN:
            return plan
        namespace = Namespace.of(self.hp.string(domain_token))
        limits = self.limiter.get_limits(namespace)
        compiler = NamespaceCompiler(limits, interner=self._interner)
        native_ok = compiler.fully_vectorized and all(
            # Limits the storage would route to its exact host fallback
            # (beyond-device-cap windows, non-ms-tick buckets) bypass the
            # columnar kernel — such namespaces take the exact path.
            # Device-eligible token buckets ride the fast path: their
            # hits carry the GCRA interval + bucket flag to the kernel.
            (
                limit.max_value <= K.MAX_VALUE_CAP
                if limit.policy == "fixed_window"
                else device_eligible(
                    limit.max_value, limit.seconds,
                    K.MAX_VALUE_CAP, K.WINDOW_MS_CAP,
                )
            )
            for limit in limits
        )
        if not limits or not native_ok:
            # Namespace needs the exact path (or has no limits -> cheap OK,
            # handled by an empty plan).
            plan = _NsPlan(namespace, compiler, self.hp) if not limits else None
        else:
            plan = _NsPlan(namespace, compiler, self.hp)
            for cl in compiler.limits:
                for key in cl.var_keys:
                    self._track(key)
                for m in cl.mask:
                    for key in m.keys:
                        self._track(key)
        self._plans[domain_token] = plan
        return plan

    def _track(self, key: str) -> None:
        if key not in self._tracked:
            self._tracked[key] = self.hp.track(key)

    # -- submission ----------------------------------------------------------

    async def submit(self, blob: bytes) -> bytes:
        self._loop = asyncio.get_running_loop()
        future = self._loop.create_future()
        adm = getattr(self.limiter._tpu, "admission", None)
        if adm is not None and adm.use_failover():
            # Device-plane breaker open: exact per-request path, whose
            # storage call lands on the host failover oracle.
            _spawn_detached(self._decide_exact(blob, future))
            return await future
        rid = current_request_id() if self.recorder is not None else None
        self._pending.append((blob, future, time.perf_counter(), rid))
        if self._flush_task is None or self._flush_task.done():
            self._flush_task = _spawn_detached(self._flush_soon())
        if len(self._pending) >= self.max_batch:
            await self._flush()
        return await future

    async def _flush_soon(self) -> None:
        await asyncio.sleep(self.max_delay)
        await self._flush()
        if self._pending:
            self._flush_task = _spawn_detached(self._flush_soon())

    async def _flush(self, reason: Optional[str] = None) -> None:
        batch, self._pending = self._pending, []
        if not batch:
            return
        loop = asyncio.get_running_loop()
        if self._inflight_sem is None:
            self._inflight_sem = asyncio.Semaphore(self.max_inflight)
        rec = self.recorder
        t_flush = time.perf_counter()
        batch_id = 0
        if rec is not None:
            batch_id = rec.next_batch_id()
            rec.record_flush(
                reason or (
                    "size" if len(batch) >= self.max_batch else "deadline"
                ),
                len(batch) / self.max_batch,
                [t_flush - t for _b, _f, t, _rid in batch],
            )
        # Two-phase pipelining (the MicroBatcher pattern): the host phase
        # (parse -> masks -> slots -> kernel LAUNCH) runs on the dispatch
        # thread and returns without waiting on the device; the collect
        # phase (device_get -> resolve futures) runs on collect threads.
        # Batch N+1's host phase overlaps batch N's device round trip —
        # on TPU the round trip is the dominant term, so this is where
        # the serving-path ceiling moves from 8192/RTT to 8192/host-time.
        await self._inflight_sem.acquire()
        t_submit = time.perf_counter()
        adm = getattr(self.limiter._tpu, "admission", None)
        token = adm.breaker.batch_started() if adm is not None else 0
        self._batch_seq += 1
        seq = self._batch_seq
        self._inflight_batches[seq] = batch
        try:
            (results, slow_rows, pendings), t_begin, t_staged = (
                await loop.run_in_executor(
                    self._dispatch_pool, self._timed_begin_batch,
                    [b for b, _f, _t, _rid in batch],
                )
            )
        except Exception as exc:
            self._inflight_sem.release()
            self._inflight_batches.pop(seq, None)
            if adm is not None:
                adm.breaker.batch_finished(token, exc)
            for _blob, future, _t, _rid in batch:
                if not future.done():
                    future.set_exception(exc)
            return
        # Requests the columnar path couldn't take: exact per-request path.
        for r in slow_rows:
            blob, future, _t, _rid = batch[r]
            _spawn_detached(self._decide_exact(blob, future))
        phases = {
            "dispatch": t_begin - t_submit,
            "host_stage": t_staged - t_begin,
        }
        task = loop.run_in_executor(
            self._collect_pool, self._finish_batch, batch, results, pendings,
            batch_id, t_flush, phases,
        )
        self._inflight.add(task)

        def _collected(t):
            self._inflight.discard(t)
            self._inflight_batches.pop(seq, None)
            self._inflight_sem.release()
            exc = t.exception()
            if adm is not None:
                adm.breaker.batch_finished(token, exc)
            if exc is not None:
                for _blob, future, _t, _rid in batch:
                    if not future.done():
                        future.set_exception(exc)

        task.add_done_callback(_collected)

    # -- the columnar fast path ----------------------------------------------

    def _recycle_context_if_needed(self) -> None:
        """Interner past the cap: swap in a fresh native context. Slot-map
        entries repopulate lazily through the Python key space."""
        if self.hp.interned_count() <= self.max_interned:
            return
        old = self.hp
        self.hp = native.HostPath()
        self._interner = self.hp.as_interner()
        self._tracked = {}
        self._plans = {}
        self.storage._table.native_keys.clear()
        self.storage._table.on_native_release = self.hp.slots_remove
        old.close()

    def decide_many(
        self, blobs: List[bytes], chunk: int = 8192, inflight: int = 8
    ) -> List[Optional[bytes]]:
        """Synchronous bulk engine path: raw request blobs in, response
        blobs out, zero per-request asyncio. ``None`` marks rows the
        columnar path can't take (multi-descriptor requests, namespaces
        needing the exact path) — feed those through ``submit``; rows
        whose counter allocation failed come back as the distinct
        ``STORAGE_ERROR`` sentinel (answer UNAVAILABLE, don't retry
        through submit). Up to
        ``inflight`` chunks ride the device queue at once (JAX async
        dispatch), so a high round-trip link (the axon tunnel) streams
        instead of stalling per chunk; admission stays exact because
        launches thread the state array in order. This is the
        integration surface for a native ingress that owns its own
        socket loop."""
        from collections import deque

        out: List[Optional[bytes]] = []
        window: deque = deque()  # (results, pendings), launch order

        def collect_oldest():
            results, pendings = window.popleft()
            for p in pendings:
                self._finish_namespace(p, results)
            out.extend(results)

        for ofs in range(0, len(blobs), chunk):
            part = blobs[ofs:ofs + chunk]
            with self._native_lock:
                results, _slow, pendings = self._begin_batch_locked(part)
            window.append((results, pendings))
            if len(window) > max(inflight, 1):
                collect_oldest()
        while window:
            collect_oldest()
        return out

    def _begin_batch(self, blobs: List[bytes]):
        with self._native_lock:
            return self._begin_batch_locked(blobs)

    def _timed_begin_batch(self, blobs: List[bytes]):
        """(begin result, t_start, t_end) — the dispatch-thread host phase
        with its executor-handoff and staging times exposed."""
        t_start = time.perf_counter()
        out = self._begin_batch(blobs)
        return out, t_start, time.perf_counter()

    def _begin_batch_locked(self, blobs: List[bytes]):
        """Host phase: parse, group by namespace, evaluate masks, resolve
        slots, LAUNCH kernels. Returns (results, slow_rows, pendings)
        where results rows are filled for everything decided without a
        kernel, slow_rows lists exact-path rows (left None), and each
        pending carries an in-flight device result for
        ``_finish_namespace``."""
        adm = getattr(self.limiter._tpu, "admission", None)
        if adm is not None and adm.use_failover():
            # Breaker open: every row takes the exact path (whose
            # storage call fails over to the host oracle) — the
            # columnar path would launch kernels on the dead plane.
            return [None] * len(blobs), list(range(len(blobs))), []
        self._recycle_context_if_needed()
        n = len(blobs)
        domains, hits, cols, _ndesc, extra = self.hp.parse_batch(blobs)

        results: List[Optional[bytes]] = [None] * n

        # Group rows by domain token — vectorized: the per-row Python
        # dict/append loop profiled as the single largest host cost of
        # decide_many (131k dict ops per 4x32k rows).
        unknown = domains < 0
        for r in np.nonzero(unknown)[0].tolist():
            results[r] = self.UNKNOWN_BLOB
        slow_mask = np.logical_and(~unknown, extra > 0)
        slow_rows: List[int] = np.nonzero(slow_mask)[0].tolist()
        norm_idx = np.nonzero(
            np.logical_and(~unknown, ~slow_mask)
        )[0].astype(np.int32)
        groups: List[Tuple[int, np.ndarray]] = []
        if norm_idx.size:
            toks = domains[norm_idx]
            first = int(toks[0])
            if bool((toks == first).all()):  # common case: one namespace
                groups = [(first, norm_idx)]
            else:
                order = np.argsort(toks, kind="stable")
                si, st = norm_idx[order], toks[order]
                starts = np.nonzero(
                    np.concatenate([[True], st[1:] != st[:-1]])
                )[0]
                ends = np.append(starts[1:], st.size)
                groups = [
                    (int(st[a]), si[a:b]) for a, b in zip(starts, ends)
                ]

        pendings = []
        for token, rows in groups:
            plan = self._plan_for(token)
            if plan is None:
                slow_rows.extend(rows.tolist())  # results stay None (slow)
                continue
            if not plan.limits_meta:
                for r in rows.tolist():
                    results[r] = self.OK_BLOB
                continue
            pending = self._begin_namespace(
                plan, token, rows, hits, cols, results, blobs
            )
            if pending is not None:
                pendings.append(pending)
        return results, slow_rows, pendings

    def _finish_batch(
        self, batch, results, pendings, batch_id: int = 0,
        t_flush: float = 0.0, phases: Optional[dict] = None,
    ) -> None:
        """Collect phase: block on the device results, fill the kernel-
        decided rows, resolve every settled future in ONE loop callback
        (a call_soon_threadsafe per future is a self-pipe write + wakeup
        per request — it profiled as ~45% of the serving path)."""
        with device_batch_span(batch_id, len(batch)) as span_phases:
            t_fin = time.perf_counter()
            for pending in pendings:
                self._finish_namespace(pending, results)
            t_done = time.perf_counter()
            by_loop: Dict[object, list] = {}
            for (blob, future, _t, _rid), out in zip(batch, results):
                # None marks slow-path rows (resolved later); note UNKNOWN
                # serializes to b"" (all-default proto3), which is a valid
                # response — only None is the sentinel.
                if out is not None:
                    by_loop.setdefault(
                        future.get_loop(), []).append((future, out))
            for loop, pairs in by_loop.items():
                loop.call_soon_threadsafe(_resolve_many, pairs)
            rec = self.recorder
            if phases is None:
                return
            phases["device_sync"] = t_done - t_fin
            phases["unpack"] = time.perf_counter() - t_done
            span_phases(phases)
            if rec is None:
                return
            rec.record_batch(
                (
                    (t_enq, rid, None)
                    for (_blob, _future, t_enq, rid), out
                    in zip(batch, results)
                    if out is not None  # slow-path rows decided elsewhere
                ),
                batch_id, t_flush, phases,
            )

    def _begin_namespace(
        self, plan, token, rows, hits, cols, results, blobs
    ) -> Optional["_NsPending"]:
        rows_arr = np.asarray(rows, np.int32)
        m = rows_arr.shape[0]
        needed = set()
        for cl in plan.compiler.limits:
            needed.update(cl.var_keys)
            for mask in cl.mask:
                needed.update(mask.keys)
        if any(k not in cols for k in needed):
            # First batch for this namespace: its keys were tracked after
            # the batch-wide parse. Re-parse just this group.
            _d, h2, cols_local, _n, _e = self.hp.parse_batch(
                [blobs[r] for r in rows]
            )
            group_cols = {k: cols_local[k] for k in needed}
            deltas_req = h2
        else:
            group_cols = {k: cols[k][rows_arr] for k in needed}
            deltas_req = hits[rows_arr]

        hit_slots: List[np.ndarray] = []
        hit_deltas: List[np.ndarray] = []
        hit_maxes: List[np.ndarray] = []
        hit_windows: List[np.ndarray] = []
        hit_req: List[np.ndarray] = []
        hit_fresh: List[np.ndarray] = []
        hit_bucket: List[np.ndarray] = []
        hit_name: List[Tuple[object, np.ndarray]] = []  # (limit, local req idx)
        failed_reqs: set = set()  # local idx whose allocation errored

        # Lookup -> (alloc misses) -> kernel happens under the storage lock
        # so a concurrent LRU eviction cannot recycle a looked-up slot
        # between lookup and kernel (check_columnar re-enters the RLock).
        with self.storage._lock:
            # Phase 1: evaluate + resolve slots for EVERY limit before
            # building hit arrays — a late allocation failure must void the
            # failed request's deltas on earlier limits too (all-or-nothing).
            staged = []
            for (cl, applies, var_cols), meta in zip(
                plan.compiler.evaluate_columns(group_cols, m),
                plan.limits_meta,
            ):
                limit_token, max_value, window_s, name, limit = meta
                idx = np.nonzero(applies)[0].astype(np.int32)
                if idx.size == 0:
                    continue
                k = 2 + len(var_cols)
                keys = np.empty((idx.size, k), np.int32)
                keys[:, 0] = token
                keys[:, 1] = limit_token
                for j, vc in enumerate(var_cols):
                    keys[:, 2 + j] = vc[idx]
                slots = self.hp.slots_lookup(keys)
                fresh = slots < 0
                if fresh.any():
                    self._allocate_missing(
                        limit, var_cols, idx, keys, slots, fresh, failed_reqs
                    )
                    # failed allocations leave slot -1: point them at the
                    # inert scratch cell with delta 0
                    bad = slots < 0
                    slots[bad] = self.storage._scratch
                    fresh[bad] = False
                staged.append((limit, idx, slots, fresh, max_value, window_s))

            # Phase 2: build hit arrays with failed requests fully voided.
            for limit, idx, slots, fresh, max_value, window_s in staged:
                hit_slots.append(slots.astype(np.int32))
                deltas_l = np.minimum(
                    deltas_req[idx], K.MAX_DELTA_CAP
                ).astype(np.int32)
                if failed_reqs:
                    deltas_l[np.isin(idx, list(failed_reqs))] = 0
                hit_deltas.append(deltas_l)
                hit_maxes.append(
                    np.full(idx.size, max_value, np.int32)
                )
                if limit.policy == "token_bucket":
                    win = emission_interval_ms(max_value, window_s)
                    is_bucket = True
                else:
                    win = min(window_s * 1000, 2**31 - 2**30 - 2)
                    is_bucket = False
                hit_windows.append(np.full(idx.size, win, np.int32))
                hit_req.append(idx)
                hit_fresh.append(fresh)
                hit_bucket.append(np.full(idx.size, is_bucket, bool))
                hit_name.append((limit, idx))

            namespace = str(plan.namespace)
            if not hit_slots:
                for local, r in enumerate(rows):
                    results[r] = self.OK_BLOB
                if self.metrics:
                    self.metrics.incr_authorized_calls(namespace, n=m)
                    self.metrics.incr_authorized_hits(
                        namespace, int(deltas_req.sum())
                    )
                return None

            slots = np.concatenate(hit_slots)
            deltas = np.concatenate(hit_deltas)
            maxes = np.concatenate(hit_maxes)
            windows = np.concatenate(hit_windows)
            req = np.concatenate(hit_req)
            fresh = np.concatenate(hit_fresh)
            bucket = np.concatenate(hit_bucket)
            # Kernel req ids must be dense in [0, H): requests without hits
            # don't participate, so compress local indices.
            order = np.argsort(req, kind="stable")
            participating, kernel_req = np.unique(
                req[order], return_inverse=True
            )
            arrays = self.storage.pad_hits(
                (slots[order], deltas[order], maxes[order], windows[order],
                 kernel_req.astype(np.int32), fresh[order], bucket[order]),
                slots.shape[0],
            )
            inflight = self.storage.begin_check_columnar(*arrays)
        return _NsPending(
            namespace, rows, deltas_req, failed_reqs, participating,
            order, req, hit_name, inflight,
        )

    def _finish_namespace(self, pending: "_NsPending", results) -> None:
        """Collect one namespace's device result and fill its rows."""
        namespace = pending.namespace
        rows = pending.rows
        deltas_req = pending.deltas_req
        failed_reqs = pending.failed_reqs
        participating = pending.participating
        order = pending.order
        req = pending.req
        hit_name = pending.hit_name
        admitted, hit_ok, _rem, _ttl = self.storage.finish_check_columnar(
            pending.inflight, with_remaining=False
        )
        # Requests without hits default to admitted (no counter applied);
        # fill via flat arrays — the per-row dict build/get profiled as
        # the second-largest host cost of decide_many.
        m = len(rows)
        admitted_full = np.ones(m, bool)
        admitted_full[participating] = admitted[: participating.size]
        ok_blob, over_blob = self.OK_BLOB, self.OVER_BLOB
        rows_list = rows.tolist() if isinstance(rows, np.ndarray) else rows
        for r, a in zip(rows_list, admitted_full.tolist()):
            results[r] = ok_blob if a else over_blob
        ok_mask = admitted_full
        if failed_reqs:
            failed = sorted(failed_reqs)
            for local in failed:
                results[rows_list[local]] = _STORAGE_ERROR
            ok_mask = admitted_full.copy()
            ok_mask[failed] = False
        n_ok = int(ok_mask.sum())
        ok_hits = int(deltas_req[ok_mask].sum())
        limited_rows = [
            local for local in np.nonzero(~admitted_full)[0].tolist()
            if local not in failed_reqs
        ]
        if self.metrics:
            if n_ok:
                self.metrics.incr_authorized_calls(namespace, n=n_ok)
                self.metrics.incr_authorized_hits(namespace, ok_hits)
            for local in limited_rows:
                # first failing hit in request order names the limit
                name = None
                pos = np.nonzero(req[order] == local)[0]
                for p in pos:
                    if not hit_ok[p]:
                        # recover the limit via cumulative spans
                        offset = 0
                        for limit, idx in hit_name:
                            if order[p] < offset + idx.size:
                                name = limit.name
                                break
                            offset += idx.size
                        break
                self.metrics.incr_limited_calls(namespace, name)

    def _allocate_missing(
        self, limit, var_cols, idx, keys, slots, fresh_mask, failed_reqs
    ) -> None:
        """Slot-map misses: allocate through the storage's key space (so
        LRU/eviction bookkeeping stays authoritative) and mirror into the
        native map. A per-counter StorageError fails only its own request
        (recorded in ``failed_reqs``), never the batch. Caller holds the
        storage lock."""
        var_sources = [v.source for v in limit.variables]
        storage = self.storage
        for pos in np.nonzero(fresh_mask)[0]:
            set_vars = {
                src: self.hp.string(int(var_cols[j][idx[pos]]))
                for j, src in enumerate(var_sources)
            }
            counter = Counter(limit, set_vars)
            try:
                slot, is_fresh = storage._slot_for(counter, create=True)
            except StorageError:
                failed_reqs.add(int(idx[pos]))
                continue
            # The key may already live in the Python key space (counter
            # created via the per-request path): then the cell is LIVE
            # and must not be reset by the fresh flag.
            fresh_mask[pos] = is_fresh
            key = keys[pos].copy()
            self.hp.slots_insert(key, slot)
            storage._table.native_keys[slot] = key
            slots[pos] = slot

    # -- exact fallback --------------------------------------------------------

    async def _decide_exact(self, blob: bytes, future: asyncio.Future) -> None:
        from ..server.rls import _context_from_request, _hits_addend

        try:
            req = self._pb.RateLimitRequest.FromString(blob)
            if not req.domain:
                out = self.UNKNOWN_BLOB
            else:
                ctx = _context_from_request(req)
                result = await self.limiter.check_rate_limited_and_update(
                    req.domain, ctx, _hits_addend(req), False
                )
                namespace = req.domain
                if result.limited:
                    if self.metrics:
                        self.metrics.incr_limited_calls(
                            namespace, result.limit_name
                        )
                    out = self.OVER_BLOB
                else:
                    if self.metrics:
                        self.metrics.incr_authorized_calls(namespace)
                        self.metrics.incr_authorized_hits(
                            namespace, _hits_addend(req)
                        )
                    out = self.OK_BLOB
            if not future.done():
                future.set_result(out)
        except Exception as exc:
            if not future.done():
                future.set_exception(exc)

    def fail_over_queued(self, decider, exc) -> None:
        """Admission-plane breaker trip: queued raw requests re-route
        through the exact per-request path (which lands on the host
        failover oracle); dispatched-but-uncollected batches fail with
        ``exc``. ``decider`` is unused — the exact path already decides
        through the storage's failover branch. Thread-safe."""
        loop = self._loop
        if loop is None or loop.is_closed():
            return

        def _drain():
            batch, self._pending = self._pending, []
            for blob, future, _t, _rid in batch:
                if not future.done():
                    _spawn_detached(self._decide_exact(blob, future))
            for stuck in list(self._inflight_batches.values()):
                for _blob, future, _t, _rid in stuck:
                    if not future.done():
                        future.set_exception(exc)

        loop.call_soon_threadsafe(_drain)

    async def close(self) -> None:
        if self._flush_task is not None:
            await self._flush("shutdown")
        if self._inflight:
            await asyncio.gather(*self._inflight, return_exceptions=True)
        self._dispatch_pool.shutdown(wait=False)
        self._collect_pool.shutdown(wait=False)


def _spawn_detached(coro) -> asyncio.Task:
    """Background task in a FRESH contextvars context. The spawn point
    can sit inside a request's MetricsLayer span (submit is awaited under
    the handler's should_rate_limit span): inheriting that context would
    parent the flush loop — and every slow-path decide it fans out — under
    one arbitrary request's span, folding other requests' storage time
    into its aggregate. Slow-path requests are measured by their own
    handler spans around the awaited future instead."""
    loop = asyncio.get_running_loop()
    if sys.version_info >= (3, 11):
        return loop.create_task(coro, context=contextvars.Context())
    # Python 3.10: create_task has no context kwarg, but Task captures
    # copy_context() at construction — run it inside the fresh context.
    return contextvars.Context().run(loop.create_task, coro)


def _resolve(future: asyncio.Future, value: bytes) -> None:
    if not future.done():
        future.set_result(value)


def _reject(future: asyncio.Future, exc: Exception) -> None:
    if not future.done():
        future.set_exception(exc)


def _resolve_many(pairs) -> None:
    for future, out in pairs:
        if future.done():
            continue
        if out is _STORAGE_ERROR:
            future.set_exception(
                StorageError("counter allocation failed", transient=True)
            )
        else:
            future.set_result(out)


class _NsPending:
    """One namespace's launched-but-uncollected kernel: everything
    ``_finish_namespace`` needs to turn the device result into response
    blobs and metrics."""

    __slots__ = (
        "namespace", "rows", "deltas_req", "failed_reqs", "participating",
        "order", "req", "hit_name", "inflight",
    )

    def __init__(
        self, namespace, rows, deltas_req, failed_reqs, participating,
        order, req, hit_name, inflight,
    ):
        self.namespace = namespace
        self.rows = rows
        self.deltas_req = deltas_req
        self.failed_reqs = failed_reqs
        self.participating = participating
        self.order = order
        self.req = req
        self.hit_name = hit_name
        self.inflight = inflight


class _Missing:
    pass


_MISSING_PLAN = _Missing()
_STORAGE_ERROR = _Missing()
NativeRlsPipeline.STORAGE_ERROR = _STORAGE_ERROR
